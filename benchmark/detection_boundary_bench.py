"""Host-op detection boundary timing (VERDICT r4 #7).

A Faster-R-CNN-style training step alternates compiled device segments
with the label-assignment ops this framework deliberately runs
host-side (ops/detection.py:15-19; the reference runs them as CPU-only
kernels INSIDE its graph — detection/rpn_target_assign_op.cc,
generate_proposal_labels_op.cc). This measures the actual cost of that
boundary on the chip:

  phase A (device, one jit): backbone convs -> RPN head ->
          generate_proposals (fixed-shape NMS on device)
  fetch:  proposals + scores to host
  phase B (host): rpn_target_assign + generate_proposal_labels per
          image (numpy)
  phase C (device, one jit): RoI-align + head forward/backward step on
          the sampled rois

One JSON line per phase plus the step total and the host share. The
BASELINE.md entry interprets the result against the "belongs in the
input pipeline" claim.

Run: python benchmark/detection_boundary_bench.py  (uses the ambient
device — the real chip under axon; CPU fallback works for CI).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import detection as det

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    B, IM = (8, 512) if on_tpu else (2, 128)
    steps = 20 if on_tpu else 3
    FH = IM // 16                      # C4 feature stride 16
    A = 9                              # anchors per location
    C = 256                            # feature channels
    POST = 512                         # proposals per image

    rng = np.random.RandomState(0)
    imgs = jnp.asarray(rng.rand(B, IM, IM, 3).astype(np.float32))
    # small conv backbone (4 stride-2 stages to stride 16) + RPN head
    ws = [jnp.asarray(rng.randn(3, 3, cin, cout).astype(np.float32)
                      * (2.0 / (9 * cin)) ** 0.5)
          for cin, cout in ((3, 64), (64, 128), (128, 256), (256, C))]
    w_cls = jnp.asarray(rng.randn(1, 1, C, A).astype(np.float32) * 0.01)
    w_box = jnp.asarray(rng.randn(1, 1, C, 4 * A).astype(np.float32)
                        * 0.01)
    anchors, variances = det.anchor_generator(
        np.zeros((1, C, FH, FH), np.float32),
        anchor_sizes=(32, 64, 128), aspect_ratios=(0.5, 1.0, 2.0),
        stride=(16.0, 16.0))
    im_info = jnp.asarray(
        np.tile(np.array([IM, IM, 1.0], np.float32), (B, 1)))

    def conv(x, w, stride, act=True):
        y = jax.lax.conv_general_dilated(
            x, w, (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jax.nn.relu(y) if act else y

    @jax.jit
    def phase_a(imgs):
        h = imgs
        for w in ws:
            h = conv(h, w, 2)
        # NCHW for the proposal op's layout contract; the RPN heads are
        # LINEAR (no activation) — objectness scores and box deltas
        # must span both signs or NMS/top-k see a degenerate
        # tied-at-zero distribution
        feats = jnp.transpose(h, (0, 3, 1, 2))
        cls = jnp.transpose(conv(h, w_cls, 1, act=False), (0, 3, 1, 2))
        box = jnp.transpose(conv(h, w_box, 1, act=False), (0, 3, 1, 2))
        rois, probs, n_valid = det.generate_proposals(
            cls, box, im_info, anchors, variances,
            pre_nms_top_n=2000, post_nms_top_n=POST)
        return feats, cls, box, rois, probs

    # head: RoI-align + 2 fc + cls/box losses, forward+backward
    wh1 = jnp.asarray(rng.randn(C * 7 * 7, 1024).astype(np.float32)
                      * 0.01)
    wh2 = jnp.asarray(rng.randn(1024, 81 + 4 * 81).astype(np.float32)
                      * 0.01)

    def head_loss(params, feats, rois, labels):
        wh1, wh2 = params
        pooled = det.roi_align(feats, rois.reshape(-1, 4),
                               pooled_height=7, pooled_width=7,
                               spatial_scale=1.0 / 16,
                               roi_batch_indices=jnp.repeat(
                                   jnp.arange(B), rois.shape[1]))
        flat = pooled.reshape(pooled.shape[0], -1)
        h = jax.nn.relu(flat @ wh1)
        out = h @ wh2
        logits = out[:, :81]
        onehot = jax.nn.one_hot(labels.reshape(-1), 81)
        return -jnp.mean(jnp.sum(
            jax.nn.log_softmax(logits) * onehot, -1))

    @jax.jit
    def phase_c(params, feats, rois, labels):
        loss, grads = jax.value_and_grad(head_loss)(params, feats, rois,
                                                    labels)
        return loss, grads

    gt_boxes = [np.sort(rng.rand(12, 2, 2) * IM, axis=1)
                .transpose(0, 2, 1).reshape(12, 4).astype(np.float32)
                for _ in range(B)]
    gt_classes = [rng.randint(1, 81, 12).astype(np.int32)
                  for _ in range(B)]

    anchors_np = np.asarray(anchors).reshape(-1, 4)
    variances_np = np.asarray(variances).reshape(-1, 4)
    host_split = [0.0, 0.0]    # [rpn_target_assign, proposal_labels]

    def host_phase(rois_np, cls_np, box_np):
        """The boundary under test: per-image numpy assigners.
        rpn_target_assign depends only on anchors+gt (input-pipeline-
        movable); generate_proposal_labels consumes the CURRENT step's
        proposals (must interleave)."""
        all_rois, all_labels = [], []
        for i in range(B):
            ta = time.perf_counter()
            det.rpn_target_assign(
                box_np[i].reshape(-1, 4),
                cls_np[i].reshape(-1, 1),
                anchors_np, variances_np,
                gt_boxes[i], None, [IM, IM, 1.0])
            tb = time.perf_counter()
            rois, labels, *_ = det.generate_proposal_labels(
                rois_np[i], gt_classes[i], None, gt_boxes[i],
                [IM, IM, 1.0], batch_size_per_im=POST)
            tc = time.perf_counter()
            host_split[0] += tb - ta
            host_split[1] += tc - tb
            pad = POST - rois.shape[0]
            all_rois.append(np.pad(rois, ((0, pad), (0, 0))))
            all_labels.append(np.pad(labels.reshape(-1), (0, pad)))
        return (np.stack(all_rois).astype(np.float32),
                np.stack(all_labels).astype(np.int32))

    params = (wh1, wh2)
    t_a = t_fetch = t_host = t_c = 0.0
    host_split[0] = host_split[1] = 0.0
    # warmup compiles
    feats, cls, box, rois, probs = phase_a(imgs)
    rois_np = np.asarray(rois)
    s_rois, s_labels = host_phase(rois_np, np.asarray(cls),
                                  np.asarray(box))
    loss, _ = phase_c(params, feats, jnp.asarray(s_rois),
                      jnp.asarray(s_labels))
    float(np.asarray(loss))

    host_split[0] = host_split[1] = 0.0
    for _ in range(steps):
        t0 = time.perf_counter()
        feats, cls, box, rois, probs = phase_a(imgs)
        jax.block_until_ready(rois)
        t1 = time.perf_counter()
        rois_np = np.asarray(rois)
        cls_np = np.asarray(cls)
        box_np = np.asarray(box)
        t2 = time.perf_counter()
        s_rois, s_labels = host_phase(rois_np, cls_np, box_np)
        t3 = time.perf_counter()
        loss, grads = phase_c(params, feats, jnp.asarray(s_rois),
                              jnp.asarray(s_labels))
        float(np.asarray(loss))
        t4 = time.perf_counter()
        t_a += t1 - t0
        t_fetch += t2 - t1
        t_host += t3 - t2
        t_c += t4 - t3

    ms = [round(t / steps * 1e3, 2) for t in (t_a, t_fetch, t_host, t_c)]
    total = round(sum(ms), 2)
    print(json.dumps({
        "metric": "detection_step_phase_ms",
        "device_backbone_rpn_proposals": ms[0],
        "fetch_to_host": ms[1],
        "host_assigners": ms[2],
        "host_rpn_target_assign": round(
            host_split[0] / steps * 1e3, 2),
        "host_proposal_labels": round(
            host_split[1] / steps * 1e3, 2),
        "device_head_fwd_bwd": ms[3],
        "total_ms": total,
        "host_share_pct": round(100 * ms[2] / total, 1),
        "batch": B, "image": IM, "device": dev.platform,
    }))


if __name__ == "__main__":
    main()
