"""PS transport loopback benchmark (the BASELINE.md "PS transport"
numbers): dense push/pull of a 64 MB fp32 parameter and the native
dense optimize-block kernels, one JSON line each.

Run: python benchmark/ps_transport_bench.py [--size MB] [--reps N]

The dense push measures the full server-side path the reference runs
in C++ (recv -> decode -> optimize block -> reply; ref:
operators/distributed/request_handler_impl.cc): with the native
library built, the optimizer step runs in
native/src/ps_table.cc pt_dense_* kernels. BENCH_PS_JNP=1 forces the
Python/jnp fallback step for A/B comparison.
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import paddle_tpu as pt
    from paddle_tpu.distributed import ps as psmod
    from paddle_tpu.distributed.launch import find_free_ports
    from paddle_tpu.distributed.ps import ParameterServer, PSClient

    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64, help="param MB")
    ap.add_argument("--reps", type=int, default=8)
    args = ap.parse_args()
    n = args.size * 1024 * 1024 // 4
    grad = np.ones(n, np.float32)

    if os.environ.get("BENCH_PS_JNP") == "1":
        psmod._DenseVar._native_kind = lambda self: (None, None)

    def run(optimizer):
        port = find_free_ports(1)[0]
        srv = ParameterServer(f"127.0.0.1:{port}", num_trainers=1,
                              sync_mode=False)
        srv.host_dense("w", np.zeros(n, np.float32),
                       optimizer=optimizer)
        srv.start()
        c = PSClient([f"127.0.0.1:{port}"],
                     var_ep={"w": f"127.0.0.1:{port}"}, trainer_id=0)
        c.push_grad("w", grad)           # warmup (lazy slots/native)
        t0 = time.perf_counter()
        for _ in range(args.reps):
            c.push_grad("w", grad)
        push_dt = (time.perf_counter() - t0) / args.reps
        c.pull_param("w")
        t0 = time.perf_counter()
        for _ in range(args.reps):
            c.pull_param("w")
        pull_dt = (time.perf_counter() - t0) / args.reps
        srv.stop()
        return push_dt, pull_dt

    gb = n * 4 / 1e9
    native = "jnp" if os.environ.get("BENCH_PS_JNP") == "1" else "native"
    for name, opt in (("sgd", pt.optimizer.SGDOptimizer(0.01)),
                      ("adam", pt.optimizer.AdamOptimizer(1e-3))):
        push_dt, pull_dt = run(opt)
        print(json.dumps({
            "metric": f"ps_dense_push_{name}_{native}_gbps",
            "value": round(gb / push_dt, 3), "unit": "GB/s",
            "ms_per_req": round(push_dt * 1e3, 1),
            "size_mb": args.size, "cpus": os.cpu_count()}))
        if name == "sgd":
            print(json.dumps({
                "metric": "ps_dense_pull_gbps",
                "value": round(gb / pull_dt, 3), "unit": "GB/s",
                "ms_per_req": round(pull_dt * 1e3, 1),
                "size_mb": args.size, "cpus": os.cpu_count()}))


if __name__ == "__main__":
    sys.exit(main())
