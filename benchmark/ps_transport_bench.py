"""PS transport loopback benchmark (the BASELINE.md "PS transport"
numbers): dense push/pull of a 64 MB fp32 parameter, the native dense
optimize-block kernels, small-request dispatch rates, and multi-client
fan-in — one JSON line each, for BOTH server transports.

Run: python benchmark/ps_transport_bench.py [--size MB] [--reps N]

The dense push measures the full server-side path the reference runs
in C++ (recv -> decode -> optimize block -> reply; ref:
operators/distributed/request_handler_impl.cc). Transports:
  native  — C++ accept loop / codec / dispatch / kernels
            (native/src/ps_server.cc), the SURVEY §5.8 path
  python  — the socketserver fallback in distributed/ps.py (its
            optimizer step still uses the C++ kernels)
BENCH_PS_JNP=1 additionally forces the Python server's jnp step for
the r4-era A/B.
"""

import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main():
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import paddle_tpu as pt
    from paddle_tpu.distributed import ps as psmod
    from paddle_tpu.distributed.launch import find_free_ports
    from paddle_tpu.distributed.ps import (NativeParameterServer,
                                           ParameterServer, PSClient)

    ap = argparse.ArgumentParser()
    ap.add_argument("--size", type=int, default=64, help="param MB")
    ap.add_argument("--reps", type=int, default=8)
    ap.add_argument("--small-reps", type=int, default=2000)
    args = ap.parse_args()
    n = args.size * 1024 * 1024 // 4
    grad = np.ones(n, np.float32)

    if os.environ.get("BENCH_PS_JNP") == "1":
        psmod._DenseVar._native_kind = lambda self: (None, None)

    transports = [("native", NativeParameterServer),
                  ("python", ParameterServer)]
    try:
        from paddle_tpu import native
        if not native.available():
            transports = transports[1:]
    except Exception:
        transports = transports[1:]
    if os.environ.get("BENCH_PS_JNP") == "1":
        transports = [("jnp", ParameterServer)]

    def start_server(cls, optimizer, value):
        port = find_free_ports(1)[0]
        srv = cls(f"127.0.0.1:{port}", num_trainers=1, sync_mode=False)
        srv.host_dense("w", value, optimizer=optimizer)
        srv.start()
        cl = PSClient([srv.endpoint], var_ep={"w": srv.endpoint},
                      trainer_id=0)
        return srv, cl

    # -- dense 64 MB push/pull per transport ------------------------------
    gb = n * 4 / 1e9
    for tname, cls in transports:
        for oname, opt in (("sgd", pt.optimizer.SGDOptimizer(0.01)),
                           ("adam", pt.optimizer.AdamOptimizer(1e-3))):
            srv, c = start_server(cls, opt, np.zeros(n, np.float32))
            c.push_grad("w", grad)       # warmup (lazy slots)
            t0 = time.perf_counter()
            for _ in range(args.reps):
                c.push_grad("w", grad)
            push_dt = (time.perf_counter() - t0) / args.reps
            c.pull_param("w")
            t0 = time.perf_counter()
            for _ in range(args.reps):
                c.pull_param("w")
            pull_dt = (time.perf_counter() - t0) / args.reps
            c.close()
            srv.stop()
            print(json.dumps({
                "metric": f"ps_dense_push_{oname}_{tname}_gbps",
                "value": round(gb / push_dt, 3), "unit": "GB/s",
                "ms_per_req": round(push_dt * 1e3, 1),
                "size_mb": args.size, "cpus": os.cpu_count()}))
            if oname == "sgd":
                print(json.dumps({
                    "metric": f"ps_dense_pull_{tname}_gbps",
                    "value": round(gb / pull_dt, 3), "unit": "GB/s",
                    "ms_per_req": round(pull_dt * 1e3, 1),
                    "size_mb": args.size, "cpus": os.cpu_count()}))

    # -- C-speed client: server-side capacity isolated --------------------
    # The Python client's encode/decode shares the CPU with the server
    # on 1-core hosts and caps the end-to-end number; the C++ bench
    # client (pt_ps_bench_push/pull in ps_server.cc, same wire
    # protocol) reduces the client to memcpy-speed, so these rows
    # approximate what the SERVER can sustain — against both
    # transports.
    try:
        from paddle_tpu import native as _native
        _lib = _native.get_lib() if _native.available() else None
    except Exception:
        _lib = None
    if _lib is not None:
        for tname, cls in transports:
            srv, _c = start_server(cls, pt.optimizer.SGDOptimizer(0.01),
                                   np.zeros(n, np.float32))
            _c.close()
            dt = _lib.pt_ps_bench_push(srv.host.encode(), srv.port,
                                       b"w", n, args.reps)
            dtp = _lib.pt_ps_bench_pull(srv.host.encode(), srv.port,
                                        b"w", args.reps)
            srv.stop()
            if dt > 0:
                print(json.dumps({
                    "metric": f"ps_dense_push_sgd_{tname}_cclient_gbps",
                    "value": round(gb / (dt / args.reps), 3),
                    "unit": "GB/s",
                    "ms_per_req": round(dt / args.reps * 1e3, 1),
                    "size_mb": args.size, "cpus": os.cpu_count()}))
            if dtp > 0:
                print(json.dumps({
                    "metric": f"ps_dense_pull_{tname}_cclient_gbps",
                    "value": round(gb / (dtp / args.reps), 3),
                    "unit": "GB/s",
                    "ms_per_req": round(dtp / args.reps * 1e3, 1),
                    "size_mb": args.size, "cpus": os.cpu_count()}))

    # -- small-request dispatch rate (1 KB pushes) ------------------------
    # Bandwidth hides per-request overhead; 1 KB frames expose the
    # accept/decode/dispatch cost — where retiring the Python loop
    # pays even on a 1-core host.
    small = np.ones(256, np.float32)     # 1 KB
    for tname, cls in transports:
        srv, c = start_server(cls, pt.optimizer.SGDOptimizer(0.01),
                              np.zeros(256, np.float32))
        for _ in range(50):
            c.push_grad("w", small)      # warmup
        t0 = time.perf_counter()
        for _ in range(args.small_reps):
            c.push_grad("w", small)
        dt = time.perf_counter() - t0
        c.close()
        srv.stop()
        print(json.dumps({
            "metric": f"ps_small_push_{tname}_rps",
            "value": round(args.small_reps / dt, 0), "unit": "req/s",
            "us_per_req": round(dt / args.small_reps * 1e6, 1),
            "payload_bytes": 1024, "cpus": os.cpu_count()}))

    # -- 4-client fan-in (sync rounds, 1 MB grads) ------------------------
    # The GIL test: 4 trainers push concurrently; the server must
    # decode+accumulate 4 frames per round. Python's server serializes
    # that work on the GIL; the C++ server's only serialization is the
    # per-var mutex around the accumulate itself.
    nf = 1024 * 256                      # 1 MB
    rounds = 24
    for tname, cls in transports:
        port = find_free_ports(1)[0]
        srv = cls(f"127.0.0.1:{port}", num_trainers=4, sync_mode=True)
        srv.host_dense("w", np.zeros(nf, np.float32),
                       optimizer=pt.optimizer.SGDOptimizer(0.01))
        srv.start()
        gsmall = np.ones(nf, np.float32)
        errs = []

        def trainer(tid, warm):
            try:
                c = PSClient([srv.endpoint], var_ep={"w": srv.endpoint},
                             trainer_id=tid)
                for r in range(warm):
                    c.push_grad("w", gsmall)
                    c.pull_param("w", min_round=r + 1)
                c.close()
            except Exception as e:    # pragma: no cover
                errs.append(e)

        # warmup round
        ths = [threading.Thread(target=trainer, args=(i, 1))
               for i in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        t0 = time.perf_counter()

        def trainer_run(tid):
            try:
                c = PSClient([srv.endpoint], var_ep={"w": srv.endpoint},
                             trainer_id=tid)
                for r in range(rounds):
                    c.push_grad("w", gsmall)
                    c.pull_param("w", min_round=r + 2)
                c.close()
            except Exception as e:    # pragma: no cover
                errs.append(e)

        ths = [threading.Thread(target=trainer_run, args=(i,))
               for i in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
        dt = time.perf_counter() - t0
        srv.stop()
        if errs:
            print(json.dumps({"metric": f"ps_fanin4_{tname}_error",
                              "value": str(errs[0])}))
            continue
        # aggregate: 4 trainers x rounds x (1 MB push + 1 MB pull)
        agg_gb = 4 * rounds * 2 * nf * 4 / 1e9
        print(json.dumps({
            "metric": f"ps_fanin4_{tname}_rounds_per_s",
            "value": round(rounds / dt, 2), "unit": "rounds/s",
            "aggregate_gbps": round(agg_gb / dt, 3),
            "clients": 4, "grad_mb": 1, "cpus": os.cpu_count()}))


if __name__ == "__main__":
    sys.exit(main())
