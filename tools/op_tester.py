"""Per-op microbenchmark CLI — the operators/benchmark/op_tester.cc
analog (SURVEY §2.4 benchmark/ row): time a single op's forward (and
optionally fwd+bwd) on the current device, print one JSON line per op.

    python tools/op_tester.py --op matmul flash_attention --repeat 30
    python tools/op_tester.py --list
    python tools/op_tester.py --all --preset tiny     # CI / CPU
    python tools/op_tester.py --op fused_matmul --pallas both

Presets scale shapes: "bench" (TPU-sized) and "tiny" (CPU/CI).
``--pallas on|off|both`` wraps each run in the Pallas kernel registry's
override (ops/pallas/registry.py) so any op routed through the registry
(fused_matmul, embedding_gather, fused_adam, layer_norm, ...) can be
A/B'd from the CLI; "both" prints one JSON line per body.
"""

import argparse
import contextlib
import json
import sys
import time


def _ops(preset):
    import jax
    import jax.numpy as jnp

    import paddle_tpu.layers as L
    from paddle_tpu.ops import pallas as PLK
    from paddle_tpu.ops import pallas_kernels as PK

    big = preset == "bench"
    B = 8 if big else 2
    S = 2048 if big else 64
    H = 768 if big else 16
    V = 32768 if big else 128
    IMG = 112 if big else 16
    C = 128 if big else 4
    key = jax.random.PRNGKey(0)

    def r(*shape, dtype=jnp.bfloat16):
        return jax.random.normal(key, shape, dtype)

    # name -> (fn, args, flops_or_None)
    reg = {
        "matmul": (lambda a, b: a @ b,
                   (r(4 * H, 4 * H), r(4 * H, 4 * H)),
                   2 * (4 * H) ** 3),
        "conv2d": (lambda x, w: jax.lax.conv_general_dilated(
                       x, w, (1, 1), "SAME",
                       dimension_numbers=("NCHW", "OIHW", "NCHW")),
                   (r(B, C, IMG, IMG), r(C, C, 3, 3)),
                   2 * B * C * C * 9 * IMG * IMG),
        "elementwise_add": (lambda a, b: a + b,
                            (r(B, S, H), r(B, S, H)), None),
        "reduce_sum": (lambda x: x.sum(axis=-1), (r(B, S, H),), None),
        "softmax": (lambda x: jax.nn.softmax(x, -1), (r(B, S, S),), None),
        "layer_norm": (lambda x, g, b: PK.fused_layer_norm(x, g, b),
                       (r(B * S, H, dtype=jnp.float32),
                        jnp.ones((H,)), jnp.zeros((H,))), None),
        "softmax_cross_entropy":
            (lambda x, y: PK.softmax_cross_entropy(x, y).mean(),
             (r(B * S, V, dtype=jnp.float32),
              jax.random.randint(key, (B * S,), 0, V)), None),
        "flash_attention":
            (lambda q, k, v: PK.flash_attention(q, k, v),
             (r(B, 12, S, 64), r(B, 12, S, 64), r(B, 12, S, 64)),
             4 * B * 12 * S * S * 64),
        "dense_attention":
            (lambda q, k, v: jax.nn.softmax(
                (q @ k.swapaxes(-1, -2)) * (64 ** -0.5), -1) @ v,
             (r(B, 12, S, 64), r(B, 12, S, 64), r(B, 12, S, 64)),
             4 * B * 12 * S * S * 64),
        "embedding": (lambda ids, w: w[ids],
                      (jax.random.randint(key, (B, S), 0, V),
                       r(V, H, dtype=jnp.float32)), None),
        # registry-routed ops: honor --pallas on|off|both
        "fused_matmul":
            (lambda x, w, b: PLK.dispatch("fused_matmul", x, w,
                                          bias=b, act="relu"),
             (r(4 * H, 4 * H), r(4 * H, 4 * H), r(4 * H)),
             2 * (4 * H) ** 3),
        "embedding_gather":
            (lambda w, ids: PLK.dispatch("embedding_gather", w, ids),
             (r(V, H, dtype=jnp.float32),
              jax.random.randint(key, (B * S,), 0, V)), None),
        "embedding_scatter_add":
            (lambda d, ids, u: PLK.dispatch("embedding_scatter_add",
                                            d, ids, u),
             (r(V, H, dtype=jnp.float32),
              jax.random.randint(key, (B * S,), 0, V),
              r(B * S, H, dtype=jnp.float32)), None),
        "fused_adam":
            (lambda p, g, m1, m2: PLK.dispatch(
                "fused_adam", p, g, m1, m2, 1e-3, 10.0),
             (r(4 * H * H, dtype=jnp.float32),
              r(4 * H * H, dtype=jnp.float32),
              r(4 * H * H, dtype=jnp.float32),
              jnp.abs(r(4 * H * H, dtype=jnp.float32))), None),
    }
    return reg


def run_op(name, fn, args, flops, repeat, grad=False):
    import jax
    import jax.numpy as jnp
    import numpy as np

    # fi = first inexact (differentiable) argument: grad targets it, and
    # the scan below nudges it per-iteration to defeat CSE
    fi = next((i for i, a in enumerate(args)
               if jnp.issubdtype(jnp.asarray(a).dtype, jnp.inexact)), 0)
    if grad:
        base = jax.grad(lambda *a: jnp.sum(
            jnp.asarray(fn(*a), jnp.float32)), argnums=fi)
    else:
        base = fn

    # Time the op INSIDE one compiled program: a lax.scan applies it n
    # times per dispatch, so per-dispatch latency (dominant on the
    # remote-PJRT tunnel this runs over) cannot contaminate the number.
    # The first float arg is nudged by the (traced) iteration index so
    # XLA cannot CSE the iterations into one application; the running
    # sum over output leaves keeps every iteration live.

    def chain(n):
        def body(acc, i):
            a = list(args)
            af = jnp.asarray(a[fi])
            a[fi] = af + (i * jnp.asarray(1e-30, jnp.float32)).astype(
                af.dtype)
            out = base(*a)
            leaf = jnp.asarray(jax.tree.leaves(out)[0])
            return acc + leaf.ravel()[0].astype(jnp.float32), None

        return jax.jit(lambda: jax.lax.scan(
            body, jnp.float32(0.0), jnp.arange(n))[0])

    f1, f2 = chain(repeat), chain(3 * repeat)

    def timed(f):
        t0 = time.perf_counter()
        # host fetch = the only trustworthy sync on this tunnel (see
        # bench.py: block_until_ready returned early there)
        float(np.asarray(f()))
        return time.perf_counter() - t0

    timed(f1)                           # compile + warm both programs
    timed(f2)
    t1 = min(timed(f1) for _ in range(3))
    t2 = min(timed(f2) for _ in range(3))
    # marginal cost of the extra 2n iterations: dispatch/fetch latency
    # (tens of ms on this tunnel) cancels; min-of-3 tames jitter
    dt = max((t2 - t1) / (2 * repeat), 1e-9)
    rec = {"op": name, "ms": round(dt * 1e3, 4), "grad": grad}
    if flops:
        rec["tflops"] = round(flops * (3 if grad else 1) / dt / 1e12, 3)
    return rec


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--op", nargs="*", default=None)
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--list", action="store_true")
    ap.add_argument("--repeat", type=int, default=20)
    ap.add_argument("--grad", action="store_true",
                    help="time fwd+bwd instead of fwd")
    ap.add_argument("--preset", choices=("bench", "tiny"), default=None)
    ap.add_argument("--pallas", choices=("on", "off", "both"), default=None,
                    help="force the Pallas kernel registry selection "
                         "around each timed run ('on' uses interpreter "
                         "mode on CPU); 'both' prints one line per body")
    args = ap.parse_args(argv)

    import jax
    preset = args.preset or (
        "bench" if jax.devices()[0].platform != "cpu" else "tiny")
    reg = _ops(preset)
    if args.list:
        print("\n".join(reg))
        return 0
    names = list(reg) if (args.all or not args.op) else args.op
    modes = {"both": ("off", "on")}.get(args.pallas, (args.pallas,))
    for n in names:
        if n not in reg:
            print(json.dumps({"op": n, "error": "unknown op"}))
            continue
        fn, a, flops = reg[n]
        for mode in modes:
            if mode is None:
                ctx = contextlib.nullcontext()
            else:
                from paddle_tpu.ops import pallas as plk
                ctx = plk.override(mode)
            with ctx:
                rec = run_op(n, fn, a, flops, args.repeat, grad=args.grad)
            if mode is not None:
                rec["pallas"] = mode
            print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
