"""Metrics-catalogue lint: code and docs must agree — names AND kinds.

Every metric registered in the tree (a ``counter("name", ...)`` /
``gauge(`` / ``histogram(`` call in ``paddle_tpu/`` or ``bench.py``)
must have a row in docs/OBSERVABILITY.md's catalogue table, every row
must correspond to a registered metric, and the row's *type* column
must match the factory that registered it — an undocumented metric is
invisible to operators, a documented-but-gone metric silently breaks
their dashboards, and a gauge documented as a counter makes operators
``rate()`` a value that is not monotone. Run as a tier-1 test
(tests/test_monitor.py) and standalone:

    python tools/check_metrics.py        # exit 1 on any drift
"""

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# a registration is a lowercase factory call with a literal first-arg
# name (possibly on the next line); \s* crosses newlines on purpose,
# and the factory match is a bare substring so aliased imports
# (``histogram as _histogram``) still count
_REG_RE = re.compile(
    r"(counter|gauge|histogram)\(\s*[\"']([a-zA-Z_:][a-zA-Z0-9_:]*)[\"']")
# catalogue rows: | `name` | type | ...
_DOC_RE = re.compile(
    r"^\|\s*`([a-zA-Z_:][a-zA-Z0-9_:]*)`\s*\|\s*([a-z]+)\s*\|",
    re.MULTILINE)
# the trace module's exemplar-metric declaration: every name listed
# there must be a documented HISTOGRAM (the exemplar is "the slowest
# observation of <histogram>"; an exemplar on a gauge/counter would be
# meaningless, and an undocumented one invisible)
_EXEMPLAR_RE = re.compile(
    r"EXEMPLAR_METRICS\s*=\s*\(([^)]*)\)", re.DOTALL)
_NAME_IN_TUPLE_RE = re.compile(r"[\"']([a-zA-Z_:][a-zA-Z0-9_:]*)[\"']")
# an ``outcome`` label declared on a registration (scanned in the
# registration's source window), and the ``outcome="value"`` keyword
# uses that define the vocabulary — in inc() calls and in help/doc
# strings alike (a value the help text promises must be documented too)
_OUTCOME_LABEL_RE = re.compile(
    r"labels\s*=\s*[\(\[][^)\]]*[\"']outcome[\"']")
_OUTCOME_VALUE_RE = re.compile(
    r"outcome\s*=\s*[\"']([A-Za-z0-9_]+)[\"']")
# a ``reason`` label declared on a registration, and the
# ``reason="value"`` keyword uses in the SAME file that define its
# vocabulary (the outcome convention: inc sites live with the
# registration; the lookbehind keeps ``keep_reason=`` and friends out)
_REASON_LABEL_RE = re.compile(
    r"labels\s*=\s*[\(\[][^)\]]*[\"']reason[\"']")
_REASON_VALUE_RE = re.compile(
    r"(?<![A-Za-z0-9_])reason\s*=\s*[\"']([A-Za-z0-9_]+)[\"']")
# the goodput ledger's ``phase`` label: unlike outcome counters,
# attribution sites are deliberately spread across the tree (executor,
# checkpoint, ps, launcher), so its vocabulary is every
# ``phase="..."`` keyword literal in ANY scanned file; the lookbehind
# keeps unrelated keywords (``print_phase=``) out
_PHASE_LABEL_RE = re.compile(
    r"labels\s*=\s*[\(\[][^)\]]*[\"']phase[\"']")
_PHASE_VALUE_RE = re.compile(
    r"(?<![A-Za-z0-9_])phase\s*=\s*[\"']([A-Za-z0-9_]+)[\"']")


def exemplar_metrics(repo=REPO):
    """Names declared in monitor/trace.py's EXEMPLAR_METRICS tuple
    (statically parsed — the lint must not import the tree)."""
    path = os.path.join(repo, "paddle_tpu", "monitor", "trace.py")
    try:
        with open(path) as f:
            m = _EXEMPLAR_RE.search(f.read())
    except OSError:
        return []
    if not m:
        return []
    return _NAME_IN_TUPLE_RE.findall(m.group(1))


def code_metrics(repo=REPO):
    """{name: sorted set of kinds} for every metric registered in
    paddle_tpu/ or bench.py. More than one kind for a name means two
    registration sites disagree (the registry would raise at runtime,
    but only when both import) — the lint flags it statically rather
    than letting the last os.walk hit win."""
    out = {}
    for path in _code_files(repo):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        for kind, name in _REG_RE.findall(src):
            out.setdefault(name, set()).add(kind)
    return out


def _code_files(repo):
    files = [os.path.join(repo, "bench.py")]
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(repo, "paddle_tpu")):
        dirnames[:] = [d for d in dirnames if d not in ("__pycache__",)]
        files.extend(os.path.join(dirpath, f) for f in filenames
                     if f.endswith(".py"))
    return files


def outcome_vocabularies(repo=REPO):
    """{metric name: set of ``outcome`` label values} for every
    counter registered with an ``outcome`` label. The vocabulary is
    every ``outcome="..."`` literal in the REGISTERING file — the repo
    convention keeps a counter's inc sites in the module that
    registers it, and the deliberately-coarse union errs in the SAFE
    direction: a value reaching ``inc`` through a helper variable
    (``inc(outcome=outcome)``) is still caught by its literal at the
    call site, where a per-variable attribution would silently let a
    new outcome escape the lint. Two outcome counters in one file
    over-demand each other's values — split modules if that bites."""
    out = {}
    for path in _code_files(repo):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        file_union = None
        regs = list(_REG_RE.finditer(src))
        for k, m in enumerate(regs):
            kind, name = m.group(1), m.group(2)
            # the registration call's argument window runs to the
            # NEXT registration (or EOF): a neighbor's
            # labels=("outcome",) can't bleed in and misclassify this
            # one, and a long help string can't push this one's own
            # labels out of a fixed-size window (false green)
            end = regs[k + 1].start() if k + 1 < len(regs) else len(src)
            if kind != "counter" or \
                    not _OUTCOME_LABEL_RE.search(src[m.start():end]):
                continue
            if file_union is None:
                file_union = set(_OUTCOME_VALUE_RE.findall(src))
            out.setdefault(name, set()).update(file_union)
    return out


def reason_vocabularies(repo=REPO):
    """{metric name: set of ``reason`` label values} for every
    counter registered with a ``reason`` label — same per-file-union
    contract as :func:`outcome_vocabularies` (and the same caveat:
    two reason counters in one file over-demand each other's values,
    so modules whose reason vocabularies differ must stay separate —
    the shed counter lives in resilience.py, the tenant-refusal
    counter in frontdoor.py, deliberately)."""
    out = {}
    for path in _code_files(repo):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        file_union = None
        regs = list(_REG_RE.finditer(src))
        for k, m in enumerate(regs):
            kind, name = m.group(1), m.group(2)
            end = regs[k + 1].start() if k + 1 < len(regs) else len(src)
            if kind != "counter" or \
                    not _REASON_LABEL_RE.search(src[m.start():end]):
                continue
            if file_union is None:
                file_union = set(_REASON_VALUE_RE.findall(src))
            out.setdefault(name, set()).update(file_union)
    return out


def phase_vocabularies(repo=REPO):
    """{metric name: set of ``phase`` label values} for every metric
    registered with a ``phase`` label (the goodput ledger). The
    vocabulary is the union of ``phase="..."`` keyword literals across
    ALL scanned files — attribution sites live at the instrumented
    seams throughout the tree, not in the registering module — and
    every value must appear backticked in the metric's catalogue row,
    so an operator reading docs/OBSERVABILITY.md sees the ledger's
    full phase set."""
    values = set()
    metrics = set()
    for path in _code_files(repo):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        values.update(_PHASE_VALUE_RE.findall(src))
        regs = list(_REG_RE.finditer(src))
        for k, m in enumerate(regs):
            end = regs[k + 1].start() if k + 1 < len(regs) else len(src)
            if _PHASE_LABEL_RE.search(src[m.start():end]):
                metrics.add(m.group(2))
    return {name: set(values) for name in metrics}


#: unit-suffix discipline: a name's trailing unit promises what the
#: number means, so the registration's help text must spell the SAME
#: unit — a *_bytes gauge whose help says "ms" (or says nothing) makes
#: operators guess the scale of every dashboard they build on it
_UNIT_WORDS = {
    "bytes": ("byte",),
    "ms": ("ms", "millisecond"),
    "seconds": ("second",),
}


def _unit_suffix(name):
    base = name[:-len("_total")] if name.endswith("_total") else name
    tail = base.rsplit("_", 1)[-1]
    return tail if tail in _UNIT_WORDS else None


def unit_suffix_violations(repo=REPO):
    """[(name, suffix, path)] for every *_bytes/*_ms registration
    whose source window (the call's arguments — i.e. its help text)
    never mentions the unit the suffix promises."""
    out = set()
    for path in _code_files(repo):
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        regs = list(_REG_RE.finditer(src))
        for k, m in enumerate(regs):
            name = m.group(2)
            suffix = _unit_suffix(name)
            if suffix is None:
                continue
            end = regs[k + 1].start() if k + 1 < len(regs) else len(src)
            # window starts AFTER the name literal: the name itself
            # always contains its own suffix, which would green-wash
            # every registration
            window = src[m.end():end].lower()
            if not any(w in window for w in _UNIT_WORDS[suffix]):
                out.add((name, suffix, os.path.relpath(path, repo)))
    return sorted(out)


def doc_metrics(path=DOCS):
    """{name: documented type} from the catalogue table rows."""
    with open(path) as f:
        return {name: kind for name, kind in _DOC_RE.findall(f.read())}


def doc_rows(path=DOCS):
    """{name: full catalogue row line} — for lints that inspect a
    row's prose (e.g. the outcome-vocabulary check)."""
    rows = {}
    with open(path) as f:
        for line in f.read().splitlines():
            m = _DOC_RE.match(line)
            if m:
                rows[m.group(1)] = line
    return rows


def main():
    code = code_metrics()
    docs = doc_metrics()
    undocumented = sorted(set(code) - set(docs))
    stale = sorted(set(docs) - set(code))
    conflicted = sorted((n, sorted(ks)) for n, ks in code.items()
                        if len(ks) > 1)
    mismatched = sorted(
        (n, next(iter(code[n])), docs[n])
        for n in set(code) & set(docs)
        if len(code[n]) == 1 and docs[n] not in code[n])
    bad_exemplars = sorted(
        n for n in exemplar_metrics()
        if docs.get(n) != "histogram" or "histogram" not in
        code.get(n, set()))
    rows = doc_rows()
    missing_vocab = sorted(
        (name, v)
        for name, vocab in outcome_vocabularies().items()
        for v in sorted(vocab)
        if f"`{v}`" not in rows.get(name, ""))
    missing_phase = sorted(
        (name, v)
        for name, vocab in phase_vocabularies().items()
        for v in sorted(vocab)
        if f"`{v}`" not in rows.get(name, ""))
    missing_reason = sorted(
        (name, v)
        for name, vocab in reason_vocabularies().items()
        for v in sorted(vocab)
        if f"`{v}`" not in rows.get(name, ""))
    bad_units = unit_suffix_violations()
    if undocumented:
        print(f"metrics registered in code but missing from "
              f"docs/OBSERVABILITY.md catalogue: {undocumented}")
    if stale:
        print(f"metrics documented in docs/OBSERVABILITY.md but not "
              f"registered anywhere: {stale}")
    for name, kinds in conflicted:
        print(f"metric {name!r} is registered with conflicting kinds "
              f"across sites: {kinds}")
    for name, ck, dk in mismatched:
        print(f"metric {name!r} is registered as a {ck} but "
              f"documented as a {dk}")
    for name in bad_exemplars:
        print(f"exemplar metric {name!r} (monitor/trace.py "
              f"EXEMPLAR_METRICS) must be a registered AND documented "
              f"histogram")
    for name, v in missing_vocab:
        print(f"outcome-labeled counter {name!r} uses "
              f"outcome=\"{v}\" but its docs/OBSERVABILITY.md "
              f"catalogue row does not document `{v}` — the row must "
              f"carry the full label vocabulary")
    for name, v in missing_phase:
        print(f"phase-labeled metric {name!r} is attributed "
              f"phase=\"{v}\" somewhere in the tree but its "
              f"docs/OBSERVABILITY.md catalogue row does not document "
              f"`{v}` — the row must enumerate the ledger's full "
              f"phase vocabulary")
    for name, v in missing_reason:
        print(f"reason-labeled counter {name!r} uses "
              f"reason=\"{v}\" but its docs/OBSERVABILITY.md "
              f"catalogue row does not document `{v}` — the row must "
              f"carry the full label vocabulary")
    for name, suffix, path in bad_units:
        print(f"metric {name!r} ({path}) promises unit "
              f"'{suffix}' in its name but its registration help "
              f"text never mentions "
              f"{' or '.join(_UNIT_WORDS[suffix])!s} — unit-suffix "
              f"discipline: the help must spell the unit")
    if undocumented or stale or conflicted or mismatched \
            or bad_exemplars or missing_vocab or missing_phase \
            or missing_reason or bad_units:
        return 1
    print(f"metrics catalogue in sync ({len(code)} metrics, "
          f"kinds verified)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
