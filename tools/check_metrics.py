"""Metrics-catalogue lint: code and docs must agree.

Every metric registered in the tree (a ``counter("name", ...)`` /
``gauge(`` / ``histogram(`` call in ``paddle_tpu/`` or ``bench.py``)
must have a row in docs/OBSERVABILITY.md's catalogue table, and every
row must correspond to a registered metric — an undocumented metric is
invisible to operators, and a documented-but-gone metric silently
breaks their dashboards. Run as a tier-1 test (tests/test_monitor.py)
and standalone:

    python tools/check_metrics.py        # exit 1 on any drift
"""

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# a registration is a lowercase factory call with a literal first-arg
# name (possibly on the next line); \s* crosses newlines on purpose
_REG_RE = re.compile(
    r"(?:counter|gauge|histogram)\(\s*[\"']([a-zA-Z_:][a-zA-Z0-9_:]*)[\"']")
# catalogue rows: | `name` | type | ...
_DOC_RE = re.compile(r"^\|\s*`([a-zA-Z_:][a-zA-Z0-9_:]*)`\s*\|",
                     re.MULTILINE)


def code_metrics(repo=REPO):
    """Metric names registered anywhere in paddle_tpu/ or bench.py."""
    names = set()
    roots = [os.path.join(repo, "paddle_tpu")]
    files = [os.path.join(repo, "bench.py")]
    for root in roots:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            files.extend(os.path.join(dirpath, f) for f in filenames
                         if f.endswith(".py"))
    for path in files:
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            continue
        names.update(_REG_RE.findall(src))
    return names


def doc_metrics(path=DOCS):
    with open(path) as f:
        return set(_DOC_RE.findall(f.read()))


def main():
    code = code_metrics()
    docs = doc_metrics()
    undocumented = sorted(code - docs)
    stale = sorted(docs - code)
    if undocumented:
        print(f"metrics registered in code but missing from "
              f"docs/OBSERVABILITY.md catalogue: {undocumented}")
    if stale:
        print(f"metrics documented in docs/OBSERVABILITY.md but not "
              f"registered anywhere: {stale}")
    if undocumented or stale:
        return 1
    print(f"metrics catalogue in sync ({len(code)} metrics)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
