"""Dump a saved inference program's op list; ``--diff-passes`` runs
the program-level optimization pipeline (static/opt_passes.py) one
pass at a time and prints the op-list diff each pass produced — the
triage tool for blaming a miscompile on the guilty pass
(docs/PERFORMANCE.md "Program pass pipeline"):

    python tools/dump_program.py <model_dir>               # op list
    python tools/dump_program.py <model_dir> --diff-passes # per-pass diff
    python tools/dump_program.py <model_dir> --diff-passes \\
        --targets out.0                                    # custom roots

``<model_dir>`` is a ``save_inference_model`` directory (its
``__model__`` file is read directly — params are not loaded, nothing
executes). Targets default to the artifact's recorded fetch names.
Exit 0 always (this is a viewer, not a lint).
"""

import argparse
import difflib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:                       # CLI use from anywhere
    sys.path.insert(0, REPO)


def _op_lines(program):
    """One canonical line per op — the diff currency (indices shift as
    passes remove ops, so lines carry structure, not positions)."""
    out = []
    for op in program.global_block().ops:
        ins = ",".join(sorted(op.input_names()))
        outs = ",".join(op.output_names())
        attrs = ",".join(
            f"{k}={op.attrs[k]!r}" for k in sorted(op.attrs)
            if not k.startswith("_") and k != "name"
            and not _is_program_attr(op.attrs[k]))
        out.append(f"{op.type}({ins}) -> {outs}"
                   + (f" [{attrs}]" if attrs else ""))
    return out


def _is_program_attr(v):
    from paddle_tpu.static.program import Program
    return isinstance(v, Program)


def diff_passes(program, targets):
    """Run the default pipeline pass-by-pass; returns a list of
    ``{"pass", "ops_before", "ops_after", "diff"}`` where ``diff`` is
    the unified op-list diff lines that pass produced (empty = the
    pass was a no-op on this program)."""
    from paddle_tpu.static import opt_passes

    prog = program.clone()
    opt_passes._stamp_rng_indices(prog)
    results = []
    for p in opt_passes.default_pipeline(targets).passes:
        before = _op_lines(prog)
        out = p.apply(prog)
        prog = out if out is not None else prog
        after = _op_lines(prog)
        diff = [ln for ln in difflib.unified_diff(
            before, after, lineterm="", n=1)
            if not ln.startswith(("---", "+++", "@@"))]
        results.append({"pass": p.name, "ops_before": len(before),
                        "ops_after": len(after), "diff": diff})
    return results


def load_model_program(model_dir):
    """(program, feed_names, fetch_names) from a save_inference_model
    dir's ``__model__`` file — no params read, nothing executed."""
    from paddle_tpu.static.serialize import loads_program

    path = model_dir
    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    with open(path) as f:
        program, doc = loads_program(f.read())
    return program, doc.get("feed_names", []), doc.get("fetch_names", [])


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Dump a saved program's ops; --diff-passes prints "
                    "the op-list diff each optimization pass produced")
    ap.add_argument("model_dir",
                    help="save_inference_model dir (or a __model__ "
                         "file path)")
    ap.add_argument("--diff-passes", action="store_true",
                    help="run the pass pipeline pass-by-pass and "
                         "print each pass's op diff")
    ap.add_argument("--targets", default=None,
                    help="comma-separated DCE roots (default: the "
                         "artifact's fetch names)")
    args = ap.parse_args(argv)

    program, feeds, fetches = load_model_program(args.model_dir)
    targets = (args.targets.split(",") if args.targets else
               list(fetches))
    print(f"# feeds: {feeds}  fetches: {fetches}  targets: {targets}")
    if not args.diff_passes:
        for i, ln in enumerate(_op_lines(program)):
            print(f"[{i:3d}] {ln}")
        return 0
    total0 = len(program.global_block().ops)
    total1 = total0
    for r in diff_passes(program, targets):
        delta = r["ops_before"] - r["ops_after"]
        total1 = r["ops_after"]
        print(f"== pass {r['pass']}: {r['ops_before']} -> "
              f"{r['ops_after']} ops "
              f"({'-' + str(delta) if delta else 'no change'})")
        for ln in r["diff"]:
            print(f"   {ln}")
    print(f"== pipeline total: {total0} -> {total1} ops")
    return 0


if __name__ == "__main__":
    sys.exit(main())
