"""Public-API signature dump (tools/print_signatures.py parity).

Prints one line per public symbol: `module.name(signature)`. The
companion guard test (tests/test_api_freeze.py, the diff_api.py role)
compares this output against the committed spec so accidental API
breaks fail CI — the reference freezes its API the same way
(ref: tools/print_signatures.py, tools/diff_api.py).

Usage: python tools/print_signatures.py [--update path]
"""

import argparse
import inspect
import sys

MODULES = [
    "paddle_tpu",
    "paddle_tpu.layers",
    "paddle_tpu.ops",
    "paddle_tpu.ops.pallas",
    "paddle_tpu.optimizer",
    "paddle_tpu.static",
    "paddle_tpu.static.opt_passes",
    "paddle_tpu.io",
    "paddle_tpu.io_checkpoint",
    "paddle_tpu.nn",
    "paddle_tpu.reader",
    "paddle_tpu.metrics",
    "paddle_tpu.initializer",
    "paddle_tpu.regularizer",
    "paddle_tpu.clip",
    "paddle_tpu.inference",
    "paddle_tpu.distributions",
    "paddle_tpu.profiler",
    "paddle_tpu.monitor",
    "paddle_tpu.amp",
    "paddle_tpu.backward",
    "paddle_tpu.distributed",
    "paddle_tpu.parallel",
    "paddle_tpu.serving",
    "paddle_tpu.dataio",
    "paddle_tpu.contrib.slim",
    "paddle_tpu.contrib.quant",
    "paddle_tpu.contrib.decoder",
    "paddle_tpu.contrib.extend_optimizer",
    "paddle_tpu.contrib.layers",
    "paddle_tpu.contrib.model_stat",
    "paddle_tpu.contrib.op_frequence",
    "paddle_tpu.contrib.trainer",
    "paddle_tpu.contrib.utils",
    "paddle_tpu.transpiler",
]


def _sig(obj):
    import re
    try:
        s = str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"
    # default-value reprs that embed memory addresses are not stable
    return re.sub(r" at 0x[0-9a-fA-F]+", " at 0x...", s)


def collect():
    import importlib
    lines = []
    for modname in MODULES:
        mod = importlib.import_module(modname)
        names = getattr(mod, "__all__", None) or [
            n for n in dir(mod) if not n.startswith("_")]
        for n in sorted(set(names)):
            obj = getattr(mod, n, None)
            if obj is None or inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                lines.append(f"{modname}.{n}{_sig(obj.__init__)}")
                # dir() not vars(): inherited public methods are part of
                # the frozen surface too; getattr_static classifies
                # properties/staticmethods portably
                for mn in sorted(dir(obj)):
                    if mn.startswith("_"):
                        continue
                    raw = inspect.getattr_static(obj, mn, None)
                    if isinstance(raw, property):
                        lines.append(f"{modname}.{n}.{mn} [property]")
                    elif isinstance(raw, (staticmethod, classmethod)):
                        lines.append(
                            f"{modname}.{n}.{mn}{_sig(raw.__func__)}")
                    elif callable(raw):
                        lines.append(f"{modname}.{n}.{mn}{_sig(raw)}")
            elif callable(obj):
                lines.append(f"{modname}.{n}{_sig(obj)}")
            else:
                lines.append(f"{modname}.{n}")
    return sorted(set(lines))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", default=None,
                    help="write the spec to this path instead of stdout")
    args = ap.parse_args(argv)
    lines = collect()
    text = "\n".join(lines) + "\n"
    if args.update:
        with open(args.update, "w") as f:
            f.write(text)
        print(f"wrote {len(lines)} signatures to {args.update}",
              file=sys.stderr)
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main()
