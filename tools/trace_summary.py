#!/usr/bin/env python3
"""Summarize a jax.profiler trace directory by device-time.

The tools/timeline.py analog (ref: tools/timeline.py:131 converts the
reference's profiler proto to chrome tracing): jax already emits
chrome-trace JSON; this tool aggregates the device lanes into the
per-HLO-category / per-op table used for the roofline and residue
analyses in BASELINE.md (r2 ResNet roofline, r3 Transformer-big bound,
r3 residue attribution).

Usage:
    python tools/trace_summary.py TRACE_DIR [--steps N] [--top K]

where TRACE_DIR is the directory passed to jax.profiler.trace(...).
--steps divides totals to per-step figures.
"""

import argparse
import glob
import gzip
import json
import sys


def summarize(trace_dir, steps=1, top=15):
    paths = glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    by_cat, by_name = {}, {}
    total = 0.0
    for p in paths:
        with gzip.open(p, "rt") as f:
            doc = json.load(f)
        for e in doc.get("traceEvents", []):
            if e.get("ph") != "X":
                continue
            args = e.get("args") or {}
            cat = args.get("hlo_category")
            if cat is None:      # host lanes have no hlo_category
                continue
            dur = e.get("dur", 0)
            by_cat[cat] = by_cat.get(cat, 0.0) + dur
            key = e.get("name", "").split(".")[0][:48]
            by_name[key] = by_name.get(key, 0.0) + dur
            total += dur
    if not total:
        raise SystemExit("no device events with hlo_category found")

    def table(d, title, k):
        print(f"== {title} ==")
        for name, us in sorted(d.items(), key=lambda kv: -kv[1])[:k]:
            print(f"{name:48s} {us / steps / 1000:9.2f} ms/step "
                  f"{us / total * 100:5.1f}%")

    table(by_cat, "device time by HLO category", top)
    table(by_name, "device time by op name", top)
    print(f"device busy total: {total / steps / 1000:.2f} ms/step "
          f"({len(paths)} trace file(s), steps={steps})")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace_dir")
    ap.add_argument("--steps", type=int, default=1,
                    help="profiled step count (divides totals)")
    ap.add_argument("--top", type=int, default=15)
    a = ap.parse_args(argv)
    if a.steps <= 0:
        ap.error("--steps must be positive")
    summarize(a.trace_dir, a.steps, a.top)


if __name__ == "__main__":
    main(sys.argv[1:])
