"""TPU-pod job manifest generator.

The reference ships a kubernetes job generator for its benchmark
cluster runs (`benchmark/fluid/kube_gen_job.py` — pserver / nccl2 /
local disttypes, env-wired pods built from `kube_templates/`). This is
the TPU-native counterpart: it emits GKE-style Kubernetes manifests
for the SAME launch contract `paddle_tpu.distributed.launch` wires
locally (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / PADDLE_EXCHANGE_ENDPOINTS /
TRAINING_ROLE, and PADDLE_PSERVER_ENDPOINTS in ps mode), which
`role_maker.PaddleCloudRoleMaker.generate_role` consumes unchanged.

Design notes (TPU-first, not a port):
- collective mode is ONE indexed Job (completionMode: Indexed,
  completions == parallelism == num_hosts) plus a headless Service:
  pod DNS names are deterministic (`<job>-<i>.<job>`), so the full
  endpoint list is static env — no gen_nccl_id-style rendezvous
  bootstrap is needed, and rank 0's endpoint doubles as the
  jax.distributed coordinator exactly like launch.py's local
  contract. PADDLE_TRAINER_ID rides the downward JOB_COMPLETION_INDEX.
- TPU resources are requested as `google.com/tpu` chips with the GKE
  TPU nodeSelectors (accelerator type + topology).
- ps mode emits a pserver Job (no TPU) + a trainer Job (TPU),
  mirroring launch_ps's two process groups.

Usage:
  python tools/pod_launch.py --jobname bert --trainers 4 \
      --tpu-type tpu-v5-lite-podslice --topology 4x4 --chips-per-host 4 \
      --entry "python -u train.py" > job.yaml
"""

import argparse
import sys

__all__ = ["build_manifests", "to_yaml", "parse_args"]

_BASE_PORT = 6170


def _endpoints(name, n, port):
    """Endpoint list for job `name` behind its same-named headless
    service: indexed-pod DNS is `<name>-<i>.<name>` (pod hostname is
    `<job>-<index>`, subdomain is the service)."""
    return ",".join(f"{name}-{i}.{name}:{port}" for i in range(n))


def _headless_service(name, port, extra_port=None):
    ports = [{"name": "trainer", "port": port}]
    if extra_port is not None:
        ports.append({"name": "exchange", "port": extra_port})
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name},
        "spec": {
            "clusterIP": "None",        # the literal string — headless
            "selector": {"job-name": name},
            "ports": ports,
        },
    }


def _container(args, env, with_tpu):
    resources = {"requests": {"cpu": str(args.cpu),
                              "memory": f"{args.memory}Gi"},
                 "limits": {}}
    if with_tpu:
        resources["requests"]["google.com/tpu"] = str(args.chips_per_host)
        resources["limits"]["google.com/tpu"] = str(args.chips_per_host)
    return {
        "name": "main",
        "image": args.image,
        "command": ["/bin/sh", "-c", args.entry],
        "env": [{"name": k, "value": v} if not isinstance(v, dict)
                else {"name": k, **v} for k, v in env],
        "ports": [{"containerPort": args.port}],
        "resources": resources,
    }


def _indexed_job(name, replicas, args, env, with_tpu):
    # elastic restart policy mirrors the local launcher's --max_restarts
    # (see paddle_tpu/distributed/launch.py): with a restart budget the
    # kubelet restarts failed containers in place (OnFailure) — pod IP
    # and indexed hostname survive, so the PADDLE_* endpoint env stays
    # valid — and backoffLimitPerIndex gives each indexed pod its OWN
    # budget, matching the launcher's per-worker restarts (a job-wide
    # backoffLimit would let N transient failures spread across
    # different workers kill the whole job). The checkpoint-resume
    # guarantee (io_checkpoint.auto_checkpoint) makes the restarted
    # container continue, not start over.
    # terminationGracePeriodSeconds is the SIGTERM->SIGKILL window the
    # in-pod CheckpointManager.wait() flush relies on at preemption.
    spec = {
        "parallelism": replicas,
        "completions": replicas,
        "completionMode": "Indexed",
        "template": {
            "metadata": {"labels": {"job-name": name}},
            "spec": {
                "subdomain": name,      # pairs with headless Service
                "restartPolicy": ("OnFailure" if args.max_restarts
                                  else "Never"),
                "terminationGracePeriodSeconds": args.grace_period,
                "containers": [_container(args, env, with_tpu)],
            },
        },
    }
    if args.max_restarts:
        # backoffLimit must be unset when backoffLimitPerIndex is used
        spec["backoffLimitPerIndex"] = args.max_restarts
    else:
        spec["backoffLimit"] = 0
    if with_tpu:
        spec["template"]["spec"]["nodeSelector"] = {
            "cloud.google.com/gke-tpu-accelerator": args.tpu_type,
            "cloud.google.com/gke-tpu-topology": args.topology,
        }
    return {"apiVersion": "batch/v1", "kind": "Job",
            "metadata": {"name": name}, "spec": spec}


_INDEX_REF = {"valueFrom": {"fieldRef": {"fieldPath":
    "metadata.annotations['batch.kubernetes.io/job-completion-index']"}}}


def _identity_env(job, svc, n_trainers, port):
    # rank rides the indexed-Job downward annotation; the pod's own
    # endpoint expands from it (an indexed pod's stable hostname is
    # `<job>-<index>`, NOT its pod name, which carries a random
    # suffix); the rest is static because headless-service DNS is
    # deterministic
    return [
        ("PADDLE_TRAINER_ID", _INDEX_REF),
        ("PADDLE_TRAINERS_NUM", str(n_trainers)),
        ("PADDLE_CURRENT_ENDPOINT",
         f"{job}-$(PADDLE_TRAINER_ID).{svc}:{port}"),
    ]


def build_manifests(args):
    """Return the manifest dicts for the requested disttype."""
    port, xport = args.port, args.port + 1
    if args.disttype == "local":
        env = [("PADDLE_TRAINER_ID", "0"), ("PADDLE_TRAINERS_NUM", "1"),
               ("TRAINING_ROLE", "TRAINER")]
        return [_indexed_job(args.jobname, 1, args, env, with_tpu=True)]
    if args.disttype == "collective":
        eps = _endpoints(args.jobname, args.trainers, port)
        xeps = _endpoints(args.jobname, args.trainers, xport)
        env = _identity_env(args.jobname, args.jobname, args.trainers,
                            port) + [
            ("PADDLE_TRAINER_ENDPOINTS", eps),
            ("PADDLE_EXCHANGE_ENDPOINTS", xeps),
            ("TRAINING_ROLE", "TRAINER"),
        ]
        return [
            _headless_service(args.jobname, port, xport),
            _indexed_job(args.jobname, args.trainers, args, env,
                         with_tpu=True),
        ]
    if args.disttype == "pserver":
        ps_name = args.jobname + "-pserver"
        tr_name = args.jobname + "-trainer"
        ps_eps = _endpoints(ps_name, args.pservers, port)
        tr_eps = _endpoints(tr_name, args.trainers, port)
        ps_env = [
            ("PADDLE_TRAINER_ID", _INDEX_REF),
            ("PADDLE_TRAINERS_NUM", str(args.trainers)),
            ("PADDLE_PSERVER_ENDPOINTS", ps_eps),
            ("PADDLE_CURRENT_ENDPOINT",
             f"{ps_name}-$(PADDLE_TRAINER_ID).{ps_name}:{port}"),
            ("TRAINING_ROLE", "PSERVER"),
        ]
        tr_env = _identity_env(tr_name, tr_name, args.trainers,
                               port) + [
            ("PADDLE_PSERVER_ENDPOINTS", ps_eps),
            ("PADDLE_TRAINER_ENDPOINTS", tr_eps),
            ("TRAINING_ROLE", "TRAINER"),
        ]
        return [
            _headless_service(ps_name, port),
            _headless_service(tr_name, port),
            _indexed_job(ps_name, args.pservers, args, ps_env,
                         with_tpu=False),
            _indexed_job(tr_name, args.trainers, args, tr_env,
                         with_tpu=True),
        ]
    raise ValueError(f"unknown disttype {args.disttype!r}")


def to_yaml(manifests):
    import yaml
    return yaml.safe_dump_all(manifests, sort_keys=False,
                              default_flow_style=False)


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="pod_launch",
        description="generate TPU-pod kubernetes job manifests "
                    "(kube_gen_job.py parity, GKE TPU form)")
    ap.add_argument("--jobname", default="paddlejob")
    ap.add_argument("--image", default="paddle-tpu:latest")
    ap.add_argument("--entry", default="python -u train.py")
    ap.add_argument("--disttype", default="collective",
                    choices=["collective", "pserver", "local"])
    ap.add_argument("--trainers", type=int, default=1,
                    help="trainer hosts (one process per TPU host)")
    ap.add_argument("--pservers", type=int, default=1,
                    help="ps mode: pserver pod count")
    ap.add_argument("--tpu-type", default="tpu-v5-lite-podslice",
                    help="GKE TPU accelerator nodeSelector value")
    ap.add_argument("--topology", default="2x4",
                    help="GKE TPU topology nodeSelector value")
    ap.add_argument("--chips-per-host", type=int, default=4)
    ap.add_argument("--cpu", type=int, default=8,
                    help="CPU cores per pod")
    ap.add_argument("--memory", type=int, default=32,
                    help="memory per pod, GiB")
    ap.add_argument("--port", type=int, default=_BASE_PORT)
    ap.add_argument("--max-restarts", type=int, default=0,
                    dest="max_restarts",
                    help="per-worker restart budget: >0 emits "
                         "restartPolicy OnFailure (in-place container "
                         "restarts, endpoints preserved) with this "
                         "backoffLimitPerIndex; 0 keeps the fail-fast "
                         "Never/backoffLimit-0 policy")
    ap.add_argument("--grace-period", type=int, default=30,
                    dest="grace_period",
                    help="terminationGracePeriodSeconds: the "
                         "SIGTERM->SIGKILL window for the checkpoint "
                         "flush on preemption")
    ap.add_argument("-o", "--output", default=None,
                    help="write here instead of stdout")
    return ap.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    text = to_yaml(build_manifests(args))
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    else:
        sys.stdout.write(text)


if __name__ == "__main__":
    main()
