#!/usr/bin/env python3
"""Offline goodput waterfall: where did the job's wall-clock go?

Merges everything a supervised run (``paddle_tpu.distributed.launch``
with ``--log_dir``) leaves behind —

- ``<log_dir>/goodput/incarnations.jsonl``: one record per gang
  incarnation (attempt, world size, lifetime, labeled exit code, the
  replay watermark, and each rank's per-phase ledger at gang end);
- ``<log_dir>/heartbeat/rank*.prom``: the final per-rank metric
  snapshots (the live view for a job still running / a record-less
  single incarnation);
- ``<log_dir>/traces/*`` (when present): named so the reader knows
  deeper per-step evidence exists (tools/trace_summary.py, the merged
  <log_dir>/trace.json).

— into one per-incarnation waterfall naming the top time sinks with
where-in-the-tree evidence: which restart, which phase, how many
replayed steps (docs/DEBUGGING.md "Where did my wall-clock go?").

Usage:
    python tools/goodput_report.py LOG_DIR [--json]

Exit code 0; a log dir with no goodput evidence at all exits 2.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from paddle_tpu.monitor import exporter as _exporter       # noqa: E402
from paddle_tpu.monitor import goodput as _goodput         # noqa: E402

#: phase -> (what it is, where the seconds were attributed) — the
#: "file:line-style" evidence column of the waterfall
PHASE_EVIDENCE = {
    "device_compute": (
        "compiled-step dispatch + fetch (goodput)",
        "paddle_tpu/static/executor.py:Executor.run on_run_end split"),
    "compile": (
        "XLA trace/compile (first step, retrace, cache replay)",
        "paddle_tpu/static/executor.py:Executor.run prepare+dispatch "
        "of runs where trace_count moved"),
    "replay": (
        "re-executing steps a crash already paid for",
        "paddle_tpu/io_checkpoint.py:auto_checkpoint steps <= the "
        "crashed incarnation's last_step (incarnations.jsonl)"),
    "input_wait": (
        "input pipeline couldn't keep up",
        "paddle_tpu/static/executor.py:background_prefetch consumer "
        "q.get()"),
    "device_idle": (
        "between-step host time no instrumented stall claims",
        "paddle_tpu/monitor/goodput.py:on_run_start residual"),
    "checkpoint_save": (
        "synchronous part of checkpoint save (d2h + enqueue/write)",
        "paddle_tpu/io_checkpoint.py:CheckpointManager.save / wait"),
    "checkpoint_restore": (
        "checkpoint restore incl. verification walk-back",
        "paddle_tpu/io_checkpoint.py:CheckpointManager.restore"),
    "collective_wait": (
        "blocked on the fleet (barrier / reconnect backoff)",
        "paddle_tpu/distributed/ps.py:PSClient.barrier and reconnect"),
    "startup": (
        "process spawn to ledger arming (imports, jax init, build)",
        "paddle_tpu/monitor/goodput.py:install_from_env vs "
        "PADDLE_SPAWN_WALLTIME"),
    "restart_downtime": (
        "gang death to next spawn, x new world size",
        "paddle_tpu/distributed/launch.py:launch_collective restart "
        "backoff"),
}


def _fmt_s(v):
    return f"{v:8.2f}s"


def _live_rank_view(log_dir):
    """{rank: {"wall_seconds", "phases"}} from the final heartbeat
    snapshots — the fallback when no incarnation record covers them."""
    hb = os.path.join(log_dir, "heartbeat")
    out = {}
    for rank, (_t, samples) in \
            _exporter.read_rank_snapshots(hb).items():
        phases = _goodput.phase_seconds_of(samples)
        if not phases:
            continue
        wall = None
        for (n, _p), v in samples.items():
            if n == "goodput_wall_seconds":
                wall = float(v)
        out[str(rank)] = {"wall_seconds": wall, "phases": phases}
    return out


def build_report(log_dir):
    """Returns ``(text, data)``: the rendered waterfall and its
    machine-readable twin. Raises SystemExit(2) when the log dir holds
    no goodput evidence (no incarnation records AND no rank snapshot
    with ledger phases)."""
    log_dir = os.path.abspath(log_dir)
    recs = _goodput.read_incarnations(os.path.join(log_dir, "goodput"))
    live = _live_rank_view(log_dir)
    if not recs and not live:
        print(f"no goodput evidence under {log_dir}: neither "
              f"goodput/incarnations.jsonl nor rank snapshots with "
              f"goodput_seconds_total — was the job launched with "
              f"--log_dir under paddle_tpu.distributed.launch?",
              file=sys.stderr)
        raise SystemExit(2)
    if not recs and live:
        # record-less live view: synthesize one open incarnation
        recs = [{"incarnation": 0, "world": len(live), "status": "live",
                 "rc": None, "rc_label": None, "last_step": None,
                 "restored_step": None, "ranks": live}]

    incarnations = []
    job_phases = {}
    prev_last = None
    for rec in recs:
        ranks = rec.get("ranks") or {}
        inc_phases = {}
        rank_rows = []
        for r in sorted(ranks, key=lambda x: int(x) if
                        str(x).isdigit() else 0):
            info = ranks[r] or {}
            phases = info.get("phases") or {}
            wall = info.get("wall_seconds")
            total = sum(phases.values())
            for k, v in phases.items():
                inc_phases[k] = inc_phases.get(k, 0.0) + float(v)
            rank_rows.append({"rank": str(r), "wall_seconds": wall,
                              "attributed_seconds": total,
                              "phases": phases})
        # replayed lost work: the previous incarnation died at
        # last_step; this one restored at restored_step and re-ran
        # (restored_step, prev_last] before making new progress
        restored = rec.get("restored_step")
        replayed = None
        if prev_last is not None and restored is not None:
            replayed = max(0, int(prev_last) - int(restored))
        lifetime = None
        if rec.get("start") is not None and rec.get("end") is not None:
            lifetime = float(rec["end"]) - float(rec["start"])
        sinks = sorted(inc_phases.items(), key=lambda kv: -kv[1])
        incarnations.append({
            "incarnation": rec.get("incarnation"),
            "world": rec.get("world"),
            "status": rec.get("status"),
            "rc": rec.get("rc"),
            "rc_label": rec.get("rc_label"),
            "lifetime_seconds": lifetime,
            "last_step": rec.get("last_step"),
            "restored_step": restored,
            "replayed_steps": replayed,
            "phases": inc_phases,
            "top_sinks": [s for s, _ in sinks[:3]],
            "ranks": rank_rows,
        })
        for k, v in inc_phases.items():
            job_phases[k] = job_phases.get(k, 0.0) + float(v)
        if rec.get("last_step") is not None:
            prev_last = rec["last_step"]

    total = sum(job_phases.values())
    goodput = (job_phases.get("device_compute", 0.0) / total) \
        if total > 0 else None
    data = {
        "log_dir": log_dir,
        "incarnations": incarnations,
        "job_phases": job_phases,
        "attributed_seconds_total": total,
        "goodput_fraction": goodput,
    }

    lines = [f"goodput report: {log_dir}",
             f"incarnations: {len(incarnations)}"]
    if goodput is not None:
        lines.append(f"job goodput: {goodput * 100.0:.1f}% "
                     f"(device_compute "
                     f"{job_phases.get('device_compute', 0.0):.2f}s "
                     f"of {total:.2f}s attributed)")
    for i, inc in enumerate(incarnations):
        lines.append("")
        head = (f"incarnation {inc['incarnation']} "
                f"(world={inc['world']}, status={inc['status']}")
        if inc["rc"] is not None:
            head += f", rc={inc['rc']}"
            if inc["rc_label"]:
                head += f" [{inc['rc_label']}]"
        head += ")"
        lines.append(head)
        if inc["lifetime_seconds"] is not None:
            lines.append(f"  lifetime: {inc['lifetime_seconds']:.2f}s"
                         + (f", reached step {inc['last_step']}"
                            if inc["last_step"] is not None else ""))
        if inc["replayed_steps"] is not None:
            lines.append(
                f"  replayed lost work: {inc['replayed_steps']} "
                f"step(s) (restored at step {inc['restored_step']}, "
                f"previous incarnation died at step "
                f"{incarnations[i - 1]['last_step']})")
        inc_total = sum(inc["phases"].values())
        for phase, secs in sorted(inc["phases"].items(),
                                  key=lambda kv: -kv[1]):
            share = (secs / inc_total * 100.0) if inc_total else 0.0
            what, where = PHASE_EVIDENCE.get(
                phase, ("(undocumented phase)", "?"))
            lines.append(f"  {_fmt_s(secs)} {share:5.1f}%  "
                         f"{phase:<18} {what}")
            lines.append(f"              {'':5}   {'':<18} "
                         f"-> {where}")
        for row in inc["ranks"]:
            wall = row["wall_seconds"]
            att = row["attributed_seconds"]
            cov = f"{att / wall * 100.0:5.1f}%" if wall else "    ?"
            lines.append(f"  rank {row['rank']}: attributed "
                         f"{att:.2f}s of wall "
                         f"{wall:.2f}s ({cov} covered)"
                         if wall is not None else
                         f"  rank {row['rank']}: attributed "
                         f"{att:.2f}s (no wall gauge)")
    traces = os.path.join(log_dir, "traces")
    if os.path.isdir(traces) and os.listdir(traces):
        lines.append("")
        lines.append(
            f"per-step evidence: rank traces in {traces} (merge: "
            f"{os.path.join(log_dir, 'trace.json')}; summarize: "
            f"tools/trace_summary.py)")
    return "\n".join(lines), data


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="per-incarnation goodput waterfall from a "
                    "launcher log dir")
    ap.add_argument("log_dir", help="--log_dir of the supervised run")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report instead")
    args = ap.parse_args(argv)
    text, data = build_report(args.log_dir)
    if args.json:
        print(json.dumps(data, indent=2, sort_keys=True))
    else:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
