"""Offline checkpoint verifier: walk a checkpoint dir, verify every
shard's integrity record, report per-step status.

The same digests ``CheckpointManager.restore()`` checks on load
(io_checkpoint.verify_shard), runnable before a job is pointed at a
checkpoint dir — a bad disk found by fsck is a restart budget NOT spent
discovering it in production. Tier-1 tested (tests/test_ckpt_integrity)
and standalone:

    python tools/fsck_checkpoint.py <ckpt_dir>                # report
    python tools/fsck_checkpoint.py <ckpt_dir> --quarantine   # + rename
                                                # corrupt steps *.corrupt

Per-step statuses:

- ``ok``          meta + all shards present, every digest verifies
- ``legacy``      verifies structurally but predates the integrity
                  format (no CRCs recorded) — restorable, not provable
- ``corrupt``     a shard is unreadable or fails digest verification
- ``incomplete``  meta exists but a shard it promises is missing

Also reported: quarantined steps already renamed ``*.corrupt``, and
stray write temps (a killed writer's leftovers; the manager sweeps its
own on init). Exit code 0 when every step is ok/legacy, 1 otherwise
(incomplete counts: a step that cannot restore is a failure an
operator should know about before they need it).
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TMP_RE = re.compile(r"(\.tmp\.npz|\.json\.tmp)$")


def _name_res():
    """The writer's own filename grammar (io_checkpoint), imported
    lazily so --help works without jax on the path."""
    from paddle_tpu.io_checkpoint import META_NAME_RE, SHARD_NAME_RE
    return META_NAME_RE, SHARD_NAME_RE


def fsck_dir(dirname):
    """Verify every checkpoint step under ``dirname``.

    Returns ``(steps, extras)``: ``steps`` is a list of
    ``{"step", "status", "detail", "shards"}`` sorted by step;
    ``extras`` is ``{"quarantined": [...], "tmp": [...],
    "orphan_shards": [...]}`` (shards with no meta — an interrupted
    save whose meta never published, or a hand-deleted meta)."""
    from paddle_tpu.io_checkpoint import (
        CheckpointCorruptError, verify_shard,
    )
    meta_re, shard_re = _name_res()
    names = sorted(os.listdir(dirname))
    metas, shards = {}, {}
    extras = {"quarantined": [], "tmp": [], "orphan_shards": []}
    for f in names:
        m = meta_re.match(f)
        if m:
            metas[int(m.group(1))] = f
            continue
        m = shard_re.match(f)
        if m:
            shards.setdefault(int(m.group(1)), {})[int(m.group(2))] = f
            continue
        if f.endswith(".corrupt"):
            extras["quarantined"].append(f)
        elif _TMP_RE.search(f):
            extras["tmp"].append(f)
    for s in sorted(set(shards) - set(metas)):
        extras["orphan_shards"].extend(shards[s].values())

    steps = []
    for s in sorted(metas):
        rec = {"step": s, "status": "ok", "detail": "", "shards": {}}
        steps.append(rec)
        try:
            with open(os.path.join(dirname, metas[s])) as f:
                nproc = int(json.load(f).get("nproc", 1))
        except (OSError, ValueError, TypeError) as e:
            rec["status"] = "corrupt"
            rec["detail"] = (f"meta {metas[s]} unreadable "
                             f"({type(e).__name__}: {e})")
            continue
        legacy = False
        for p in range(nproc):
            fname = f"ckpt_{s}.shard{p}.npz"
            path = os.path.join(dirname, fname)
            if not os.path.exists(path):
                rec["shards"][fname] = "missing"
                rec["status"] = "incomplete"
                rec["detail"] = (f"meta promises {nproc} shard(s) but "
                                 f"{fname} is missing")
                continue
            try:
                manifest, arrays = verify_shard(path)
            except CheckpointCorruptError as e:
                rec["shards"][fname] = "corrupt"
                if rec["status"] != "incomplete":
                    rec["status"] = "corrupt"
                    rec["detail"] = str(e)
                continue
            if manifest.get("integrity") is None:
                rec["shards"][fname] = "legacy"
                legacy = True
            else:
                rec["shards"][fname] = (
                    f"ok ({len(arrays)} arrays, "
                    f"{sum(a.nbytes for a in arrays.values())} bytes)")
        if rec["status"] == "ok" and legacy:
            rec["status"] = "legacy"
            rec["detail"] = ("predates the integrity format — "
                            "restorable, digests not provable")
    return steps, extras


def quarantine_step(dirname, step):
    """Rename a step's meta + shards ``*.corrupt`` (what restore()'s
    walk-back does on a verification failure)."""
    meta_re, shard_re = _name_res()
    renamed = []
    for f in sorted(os.listdir(dirname)):
        m = meta_re.match(f) or shard_re.match(f)
        if m and int(m.group(1)) == step:
            os.replace(os.path.join(dirname, f),
                       os.path.join(dirname, f + ".corrupt"))
            renamed.append(f + ".corrupt")
    return renamed


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fsck_checkpoint",
        description="verify every checkpoint shard digest under a dir")
    ap.add_argument("ckpt_dir")
    ap.add_argument("--quarantine", action="store_true",
                    help="rename corrupt/incomplete steps *.corrupt so "
                         "restore() skips them without paying the "
                         "verify-and-walk-back at job start")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.ckpt_dir):
        print(f"fsck_checkpoint: {args.ckpt_dir}: not a directory",
              file=sys.stderr)
        return 2
    steps, extras = fsck_dir(args.ckpt_dir)
    bad = 0
    for rec in steps:
        line = f"step {rec['step']}: {rec['status']}"
        if rec["detail"]:
            line += f" — {rec['detail']}"
        print(line)
        for fname, st in sorted(rec["shards"].items()):
            print(f"  {fname}: {st}")
        if rec["status"] not in ("ok", "legacy"):
            bad += 1
            if args.quarantine:
                for r in quarantine_step(args.ckpt_dir, rec["step"]):
                    print(f"  quarantined -> {r}")
    for kind, files in sorted(extras.items()):
        for f in files:
            print(f"{kind}: {f}")
    good = [r for r in steps if r["status"] in ("ok", "legacy")]
    print(f"# {len(steps)} step(s): {len(good)} restorable, {bad} bad; "
          f"newest restorable: "
          f"{good[-1]['step'] if good else 'NONE'}")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
