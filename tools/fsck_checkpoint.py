"""Offline checkpoint verifier: walk a checkpoint dir, verify every
shard's integrity record, report per-step status.

The same digests ``CheckpointManager.restore()`` checks on load
(io_checkpoint.verify_shard), runnable before a job is pointed at a
checkpoint dir — a bad disk found by fsck is a restart budget NOT spent
discovering it in production. Tier-1 tested (tests/test_ckpt_integrity)
and standalone:

    python tools/fsck_checkpoint.py <ckpt_dir>                # report
    python tools/fsck_checkpoint.py <ckpt_dir> --quarantine   # + rename
                                                # corrupt steps *.corrupt
    python tools/fsck_checkpoint.py <ckpt_dir> --nproc N      # also check
                                  # restorability at a target world size

Each step's line reports the WRITER TOPOLOGY — ``nproc`` and the
per-host shard list — so an operator can see what a directory can
restore onto *before* launching. ``--nproc N`` additionally judges
every step against a target world size (N == written nproc always
fits; any other N needs the reshard metadata ``array_info`` in every
shard passing the cross-writer fitness checks, or a single-host
replicated step) and the run exits 1 if no step is restorable at N —
or if the NEWEST healthy step is not (``restore()`` refuses with
``CheckpointTopologyError`` rather than silently falling back past
healthy state, and fsck's verdict must match).

Per-step statuses:

- ``ok``          meta + all shards present, every digest verifies
- ``legacy``      verifies structurally but predates the integrity
                  format (no CRCs recorded) — restorable, not provable
- ``corrupt``     a shard's (or the meta's) content is torn/rotted or
                  fails digest verification
- ``unreadable``  an I/O error (shard or meta) persisted through
                  retries — retry the fsck before trusting the
                  verdict (NOT proven corrupt)
- ``incomplete``  meta exists but a shard it promises is missing

Also reported: quarantined steps already renamed ``*.corrupt``, and
stray write temps (a killed writer's leftovers; the manager sweeps its
own on init). Exit code 0 when every step is ok/legacy, 1 otherwise
(incomplete counts: a step that cannot restore is a failure an
operator should know about before they need it).

Pserver snapshot dirs (``launch_ps --ps_snapshot_secs``'s
``<log_dir>/ps_state``) are recognized too: every generation-tagged
artifact set (``pserver_<endpoint>.gen<G>.npz`` + per-table npz +
meta) gets the same per-generation ok/legacy/corrupt/unreadable/
incomplete verdicts against the digests the warm boot
(``distributed/ps.py _ps_checkpoint_load``) verifies, plus per-file
verdicts for legacy un-generational ``pserver_*.npz`` artifacts.
``--quarantine`` renames corrupt generations ``*.corrupt`` under the
same transient-I/O-is-not-corruption rule (``unreadable`` is NEVER
renamed).
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_TMP_RE = re.compile(r"(\.tmp\.npz|\.json\.tmp)$")


def _name_res():
    """The writer's own filename grammar (io_checkpoint), imported
    lazily so --help works without jax on the path."""
    from paddle_tpu.io_checkpoint import META_NAME_RE, SHARD_NAME_RE
    return META_NAME_RE, SHARD_NAME_RE


def fsck_dir(dirname):
    """Verify every checkpoint step under ``dirname``.

    Returns ``(steps, extras)``: ``steps`` is a list of
    ``{"step", "status", "detail", "shards", "nproc", "reshardable"}``
    sorted by step (``nproc`` = the writer topology from the meta,
    None when the meta is unreadable; ``reshardable`` = every shard
    carries the ``array_info`` reshard metadata, i.e. the step can
    restore onto a *different* world size); ``extras`` is
    ``{"quarantined": [...], "tmp": [...], "orphan_shards": [...]}``
    (shards with no meta — an interrupted save whose meta never
    published, or a hand-deleted meta)."""
    from paddle_tpu.io_checkpoint import (
        CheckpointCorruptError, _retry_transient, _stat_exists,
        verify_shard,
    )
    meta_re, shard_re = _name_res()
    names = sorted(os.listdir(dirname))
    metas, shards = {}, {}
    extras = {"quarantined": [], "tmp": [], "orphan_shards": []}
    for f in names:
        m = meta_re.match(f)
        if m:
            metas[int(m.group(1))] = f
            continue
        m = shard_re.match(f)
        if m:
            shards.setdefault(int(m.group(1)), {})[int(m.group(2))] = f
            continue
        if f.endswith(".corrupt"):
            extras["quarantined"].append(f)
        elif _TMP_RE.search(f):
            extras["tmp"].append(f)
    for s in sorted(set(shards) - set(metas)):
        extras["orphan_shards"].extend(shards[s].values())

    steps = []
    for s in sorted(metas):
        rec = {"step": s, "status": "ok", "detail": "", "shards": {},
               "nproc": None, "reshardable": False}
        steps.append(rec)
        def read_nproc(fname=metas[s]):
            with open(os.path.join(dirname, fname)) as f:
                return int(json.load(f).get("nproc", 1))

        try:
            nproc = _retry_transient(read_nproc,
                                     f"checkpoint meta {metas[s]} read")
        except (ValueError, TypeError) as e:
            # garbage CONTENT: positive corruption evidence
            rec["status"] = "corrupt"
            rec["detail"] = (f"meta {metas[s]} unreadable "
                             f"({type(e).__name__}: {e})")
            continue
        except OSError as e:
            # persistent I/O failure through retries — same rule as
            # the shard read below: never proven corrupt, never
            # renamed by --quarantine (a sick mount must not demote a
            # good checkpoint)
            rec["status"] = "unreadable"
            rec["detail"] = (f"I/O error reading meta {metas[s]} "
                             f"({type(e).__name__}: {e}) — retry "
                             f"before trusting this verdict")
            continue
        rec["nproc"] = nproc
        legacy = False
        reshardable = True
        step_manifests = {}
        for p in range(nproc):
            fname = f"ckpt_{s}.shard{p}.npz"
            path = os.path.join(dirname, fname)
            try:
                # _stat_exists, not os.path.exists: exists() swallows
                # a stat blip into "missing", and 'incomplete' steps
                # ARE renamed by --quarantine — an I/O error must
                # surface as unreadable (never renamed) instead
                present = _stat_exists(path)
            except OSError as e:
                rec["shards"][fname] = "unreadable"
                if rec["status"] == "ok":
                    rec["status"] = "unreadable"
                    rec["detail"] = (f"I/O error probing {fname} "
                                     f"({type(e).__name__}: {e}) — "
                                     f"retry before trusting this "
                                     f"verdict")
                continue
            if not present:
                rec["shards"][fname] = "missing"
                rec["status"] = "incomplete"
                rec["detail"] = (f"meta promises {nproc} shard(s) but "
                                 f"{fname} is missing")
                continue
            try:
                manifest, arrays = verify_shard(path)
            except CheckpointCorruptError as e:
                rec["shards"][fname] = "corrupt"
                if rec["status"] != "incomplete":
                    rec["status"] = "corrupt"
                    rec["detail"] = str(e)
                continue
            except OSError as e:
                # persistent I/O failure even after verify_shard's
                # retries — report it, but as unreadable-now rather
                # than proven-corrupt
                rec["shards"][fname] = "unreadable"
                if rec["status"] == "ok":
                    rec["status"] = "unreadable"
                    rec["detail"] = (f"I/O error reading {fname} "
                                     f"({type(e).__name__}: {e}) — "
                                     f"retry before trusting this "
                                     f"verdict")
                continue
            step_manifests[p] = manifest
            if manifest.get("array_info") is None:
                reshardable = False
            if manifest.get("integrity") is None:
                rec["shards"][fname] = "legacy"
                legacy = True
            else:
                rec["shards"][fname] = (
                    f"ok ({len(arrays)} arrays, "
                    f"{sum(a.nbytes for a in arrays.values())} bytes)")
        rec["reshardable"] = (rec["status"] in ("ok", "legacy")
                              and reshardable)
        if rec["reshardable"] and nproc > 1 \
                and len(step_manifests) == nproc:
            why = _reshard_blocker(step_manifests)
            if why:
                rec["reshardable"] = False
                rec["reshard_blocker"] = why
        if rec["status"] == "ok" and legacy:
            rec["status"] = "legacy"
            rec["detail"] = ("predates the integrity format — "
                            "restorable, digests not provable")
    return steps, extras


def _reshard_blocker(manifests):
    """The cross-writer fitness checks ``CheckpointManager``'s reshard
    planner runs, computed offline from the manifests fsck already
    read — the SAME ``io_checkpoint._cross_writer_blocker`` the
    manager raises ``CheckpointTopologyError`` on, imported rather
    than re-implemented so a new fitness rule can never make
    ``--nproc``'s verdict drift from ``restore()``'s behavior."""
    from paddle_tpu.io_checkpoint import _cross_writer_blocker
    return _cross_writer_blocker(manifests)


def restorable_at(rec, target_nproc):
    """(fits, reason) — can this fsck step record restore onto
    ``target_nproc`` hosts? Mirrors CheckpointManager's rules: the
    written world size always fits; a single-host step fits anywhere
    (replicated fallback / reshard both read the one shard); any other
    size needs the reshard metadata in every shard AND the cross-writer
    fitness checks (``_reshard_blocker``) to pass."""
    if rec["status"] not in ("ok", "legacy"):
        return False, rec["status"]
    if rec["nproc"] == target_nproc:
        return True, "written at this world size"
    if rec["reshardable"]:
        return True, f"reshard from nproc={rec['nproc']}"
    if rec["nproc"] == 1:
        return True, "single-host step (replicated fallback)"
    return False, rec.get("reshard_blocker") or (
        f"shards predate the reshard metadata "
        f"(written nproc={rec['nproc']}, no array_info)")


def fsck_ps_dir(dirname):
    """Verify every pserver snapshot generation under ``dirname``.

    Returns ``(gens, extras)``: ``gens`` is a list of
    ``{"endpoint", "gen", "status", "detail", "artifacts"}`` sorted by
    (endpoint, gen) — one record per generation-tagged artifact set
    (meta + dense npz + per-table npz), statuses mirroring
    ``fsck_dir``'s (ok / legacy / corrupt / unreadable / incomplete) —
    plus one ``gen=None`` record per legacy un-generational
    ``pserver_*.npz`` artifact. ``extras`` is ``{"quarantined": [...],
    "tmp": [...], "orphan_artifacts": [...]}`` (gen artifacts whose
    meta never published — an interrupted snapshot, invisible to the
    warm boot)."""
    from paddle_tpu.distributed.ps import (
        PS_GEN_ARTIFACT_RE, PS_GEN_META_RE, _ps_gen_files,
    )
    from paddle_tpu.io_checkpoint import (
        CheckpointCorruptError, _retry_transient, _stat_exists,
        verify_npz,
    )
    names = sorted(os.listdir(dirname))
    extras = {"quarantined": [], "tmp": [], "orphan_artifacts": []}
    metas = {}                   # (tag, gen) -> meta filename
    gen_artifacts = set()        # gen-tagged npz filenames
    legacy = []                  # plain pserver_*.npz
    for f in names:
        if f.endswith(".corrupt"):
            if f.startswith("pserver_") or ".pserver_" in f:
                extras["quarantined"].append(f)
            continue
        if f.startswith(".pserver_") and (f.endswith(".tmp.npz")
                                          or f.endswith(".json.tmp")):
            extras["tmp"].append(f)
            continue
        m = PS_GEN_META_RE.match(f)
        if m:
            metas[(m.group(1), int(m.group(2)))] = f
            continue
        m = PS_GEN_ARTIFACT_RE.match(f)
        if m:
            gen_artifacts.add(f)
            continue
        if f.startswith("pserver_") and f.endswith(".npz"):
            legacy.append(f)

    def verdict(rec, fname, path):
        """One artifact's verdict folded into the record (the same
        precedence fsck_dir uses: incomplete > corrupt > unreadable)."""
        try:
            present = _stat_exists(path)
        except OSError as e:
            rec["artifacts"][fname] = "unreadable"
            if rec["status"] == "ok":
                rec["status"] = "unreadable"
                rec["detail"] = (f"I/O error probing {fname} "
                                 f"({type(e).__name__}: {e}) — retry "
                                 f"before trusting this verdict")
            return
        if not present:
            rec["artifacts"][fname] = "missing"
            rec["status"] = "incomplete"
            rec["detail"] = (f"meta promises {fname} but it is "
                             f"missing")
            return
        try:
            manifest, arrays = verify_npz(path)
        except CheckpointCorruptError as e:
            rec["artifacts"][fname] = "corrupt"
            if rec["status"] != "incomplete":
                rec["status"] = "corrupt"
                rec["detail"] = str(e)
            return
        except OSError as e:
            rec["artifacts"][fname] = "unreadable"
            if rec["status"] == "ok":
                rec["status"] = "unreadable"
                rec["detail"] = (f"I/O error reading {fname} "
                                 f"({type(e).__name__}: {e}) — retry "
                                 f"before trusting this verdict")
            return
        if manifest is None:
            rec["artifacts"][fname] = "legacy"
            rec.setdefault("_legacy", True)
        else:
            rec["artifacts"][fname] = (
                f"ok ({len(arrays)} arrays, "
                f"{sum(a.nbytes for a in arrays.values())} bytes)")

    gens = []
    promised = set()
    for (tag, g) in sorted(metas):
        rec = {"endpoint": tag, "gen": g, "status": "ok",
               "detail": "", "artifacts": {}}
        gens.append(rec)
        # a generation WITH a meta is never "orphaned", even when the
        # meta turns out corrupt/unreadable below — listing its (still
        # healthy) artifacts under 'orphan_artifacts: meta never
        # published' would contradict the generation's own verdict
        gen_pat = re.compile(
            rf"^pserver_{re.escape(tag)}(?:_.+)?\.gen{g}\.npz$")
        promised.update(a for a in gen_artifacts if gen_pat.match(a))

        def read_meta(fname=metas[(tag, g)]):
            with open(os.path.join(dirname, fname)) as f:
                return json.load(f)

        try:
            meta = _retry_transient(
                read_meta, f"pserver meta {metas[(tag, g)]} read")
            tables = list(meta.get("tables", []))
            # elastic-fleet records (docs/ELASTIC_TRAINING.md
            # "Resizing the pserver fleet"): the fleet epoch this
            # snapshot was serving, and whether it pinned a shard map
            rec["epoch"] = int(meta.get("epoch", 0) or 0)
            rec["has_map"] = bool(meta.get("shard_map"))
        except (ValueError, TypeError) as e:
            rec["status"] = "corrupt"
            rec["detail"] = (f"meta {metas[(tag, g)]} unreadable "
                             f"({type(e).__name__}: {e})")
            continue
        except OSError as e:
            rec["status"] = "unreadable"
            rec["detail"] = (f"I/O error reading meta "
                             f"{metas[(tag, g)]} ({type(e).__name__}: "
                             f"{e}) — retry before trusting this "
                             f"verdict")
            continue
        for path in _ps_gen_files(dirname, tag, g, tables)[:-1]:
            fname = os.path.basename(path)
            promised.add(fname)
            verdict(rec, fname, path)
        if rec["status"] == "ok" and rec.pop("_legacy", False):
            rec["status"] = "legacy"
            rec["detail"] = ("predates the integrity format — "
                            "restorable, digests not provable")
        rec.pop("_legacy", None)
    extras["orphan_artifacts"] = sorted(gen_artifacts - promised)

    for f in legacy:
        rec = {"endpoint": f[len("pserver_"):-len(".npz")],
               "gen": None, "status": "ok", "detail": "",
               "artifacts": {}}
        verdict(rec, f, os.path.join(dirname, f))
        if rec["status"] == "ok" and rec.pop("_legacy", False):
            rec["status"] = "legacy"
            rec["detail"] = ("legacy un-generational artifact — "
                            "restorable, digests not provable")
        rec.pop("_legacy", None)
        gens.append(rec)
    return gens, extras


def quarantine_ps_gen(dirname, tag, gen):
    """Rename one pserver snapshot generation's meta + artifacts
    ``*.corrupt`` (what the warm-boot walk-back does on a
    verification failure). ``gen=None`` quarantines a legacy
    un-generational artifact (``tag`` is then its filename stem)."""
    from paddle_tpu.distributed.ps import (PS_GEN_ARTIFACT_RE,
                                           PS_GEN_META_RE)
    renamed = []
    for f in sorted(os.listdir(dirname)):
        if gen is None:
            if f != f"pserver_{tag}.npz":
                continue
        else:
            m = PS_GEN_META_RE.match(f) or PS_GEN_ARTIFACT_RE.match(f)
            if not m or int(m.group(2)) != gen:
                continue
            # the artifact grammar's tag group spans table suffixes
            # (pserver_<tag>_<table>); prefix-match the endpoint tag
            if not (m.group(1) == tag or m.group(1).startswith(tag + "_")):
                continue
        os.replace(os.path.join(dirname, f),
                   os.path.join(dirname, f + ".corrupt"))
        renamed.append(f + ".corrupt")
    return renamed


def quarantine_step(dirname, step):
    """Rename a step's meta + shards ``*.corrupt`` (what restore()'s
    walk-back does on a verification failure)."""
    meta_re, shard_re = _name_res()
    renamed = []
    for f in sorted(os.listdir(dirname)):
        m = meta_re.match(f) or shard_re.match(f)
        if m and int(m.group(1)) == step:
            os.replace(os.path.join(dirname, f),
                       os.path.join(dirname, f + ".corrupt"))
            renamed.append(f + ".corrupt")
    return renamed


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="fsck_checkpoint",
        description="verify every checkpoint shard digest under a dir")
    ap.add_argument("ckpt_dir")
    ap.add_argument("--quarantine", action="store_true",
                    help="rename corrupt/incomplete steps *.corrupt so "
                         "restore() skips them without paying the "
                         "verify-and-walk-back at job start (unreadable "
                         "steps are NEVER renamed: an I/O error is not "
                         "proof of corruption)")
    ap.add_argument("--nproc", type=int, default=None,
                    help="also judge each step's restorability at this "
                         "target world size (reshard rules); exit 1 if "
                         "no step is restorable at it")
    ap.add_argument("--num-servers", type=int, default=None,
                    help="also judge whether the pserver snapshot "
                         "generations here can restore onto a fleet of "
                         "N servers (the offline check for a planned "
                         "resize): epoch-aware state (a fleet_epoch"
                         ".json or any meta with epoch >= 1) restores "
                         "at ANY N >= 1 via live migration; static "
                         "placement needs N == the snapshotted "
                         "endpoint count. Exit 1 when no generation "
                         "fits.")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.ckpt_dir):
        print(f"fsck_checkpoint: {args.ckpt_dir}: not a directory",
              file=sys.stderr)
        return 2
    steps, extras = fsck_dir(args.ckpt_dir)
    bad = 0
    fit_steps = []
    for rec in steps:
        line = f"step {rec['step']}: {rec['status']}"
        if rec["nproc"] is not None:
            # the writer topology, always in normal output: what this
            # directory can restore onto is decided before any launch
            line += (f" [written by nproc={rec['nproc']}"
                     f"{', reshardable' if rec['reshardable'] else ''}]")
        if rec["detail"]:
            line += f" — {rec['detail']}"
        print(line)
        for fname, st in sorted(rec["shards"].items()):
            print(f"  {fname}: {st}")
        if args.nproc is not None:
            fits, why = restorable_at(rec, args.nproc)
            print(f"  restorable at nproc={args.nproc}: "
                  f"{'yes' if fits else 'NO'} ({why})")
            if fits:
                fit_steps.append(rec["step"])
        if rec["status"] not in ("ok", "legacy"):
            bad += 1
            # quarantine needs POSITIVE corruption evidence; an
            # `unreadable` step (I/O error through retries) may be a
            # perfectly good checkpoint behind a sick mount — renaming
            # it would lose progress exactly like restore() quarantining
            # on a transient OSError would
            if args.quarantine and rec["status"] != "unreadable":
                for r in quarantine_step(args.ckpt_dir, rec["step"]):
                    print(f"  quarantined -> {r}")
    # pserver snapshot artifacts (launch_ps --ps_snapshot_secs state
    # dirs) get the same treatment when present — counted separately:
    # the step summary line must not report a pserver-artifact failure
    # as a bad training-checkpoint step
    ps_records, ps_extras, ps_bad = [], None, 0
    epoch_file = os.path.join(args.ckpt_dir, "fleet_epoch.json")
    has_epoch_file = os.path.isfile(epoch_file)
    if any(f.startswith("pserver_") or f.startswith(".pserver_")
           or f.startswith("psshadow_")
           for f in os.listdir(args.ckpt_dir)) or has_epoch_file:
        ps_records, ps_extras = fsck_ps_dir(args.ckpt_dir)
    if has_epoch_file:
        try:
            with open(epoch_file) as f:
                ef = json.load(f)
            print(f"fleet_epoch.json: epoch {ef.get('epoch')} "
                  f"({len((ef.get('map') or {}).get('servers', []))} "
                  f"server(s) in the committed map)")
        except (OSError, ValueError) as e:
            print(f"fleet_epoch.json: unreadable "
                  f"({type(e).__name__}: {e})")
            has_epoch_file = False
    for rec in ps_records:
        label = (f"pserver {rec['endpoint']} gen {rec['gen']}"
                 if rec["gen"] is not None
                 else f"pserver legacy artifact {rec['endpoint']}")
        line = f"{label}: {rec['status']}"
        if rec.get("epoch") is not None:
            line += (f" [epoch {rec['epoch']}"
                     f"{', shard map' if rec.get('has_map') else ''}]")
        if rec["detail"]:
            line += f" — {rec['detail']}"
        print(line)
        for fname, st in sorted(rec["artifacts"].items()):
            print(f"  {fname}: {st}")
        if rec["status"] not in ("ok", "legacy"):
            ps_bad += 1
            # same rule as the step quarantine above: POSITIVE
            # corruption evidence only — `unreadable` is never renamed
            if args.quarantine and rec["status"] != "unreadable":
                for r in quarantine_ps_gen(args.ckpt_dir,
                                           rec["endpoint"],
                                           rec["gen"]):
                    print(f"  quarantined -> {r}")
    if ps_extras:
        for kind, files in sorted(ps_extras.items()):
            for f in files:
                print(f"{kind}: {f}")
    for kind, files in sorted(extras.items()):
        for f in files:
            print(f"{kind}: {f}")
    good = [r for r in steps if r["status"] in ("ok", "legacy")]
    print(f"# {len(steps)} step(s): {len(good)} restorable, {bad} bad; "
          f"newest restorable: "
          f"{good[-1]['step'] if good else 'NONE'}")
    if ps_records:
        ps_good = [r for r in ps_records
                   if r["status"] in ("ok", "legacy")]
        by_ep = {}
        for r in ps_good:
            if r["gen"] is not None:
                by_ep.setdefault(r["endpoint"], []).append(r["gen"])
        newest = {ep: max(gs) for ep, gs in by_ep.items()}
        print(f"# pserver: {len(ps_records)} artifact set(s): "
              f"{len(ps_good)} restorable, {ps_bad} bad; newest per "
              f"endpoint: {newest if newest else 'NONE'}")
    if args.num_servers is not None:
        # the offline resize check (mirrors --nproc's verdict): which
        # fleet sizes can this pserver state restore onto?
        if args.num_servers < 1:
            print(f"# restorable at num_servers={args.num_servers}: "
                  f"NO (a pserver fleet needs >= 1 server)")
            return 1
        healthy_eps = sorted({r["endpoint"] for r in ps_records
                              if r["gen"] is not None
                              and r["status"] in ("ok", "legacy")})
        epoch_aware = has_epoch_file or any(
            (r.get("epoch") or 0) >= 1 for r in ps_records
            if r["status"] in ("ok", "legacy"))
        if not healthy_eps and not has_epoch_file:
            print(f"# restorable at num_servers={args.num_servers}: "
                  f"NO (no restorable pserver generation here)")
            return 1
        if epoch_aware:
            print(f"# restorable at num_servers={args.num_servers}: "
                  f"yes (epoch-versioned shard map: the supervisor "
                  f"resizes to any fleet size via live migration)")
        elif args.num_servers == len(healthy_eps):
            print(f"# restorable at num_servers={args.num_servers}: "
                  f"yes (static placement, matches the "
                  f"{len(healthy_eps)} snapshotted endpoint(s))")
        else:
            print(f"# restorable at num_servers={args.num_servers}: "
                  f"NO (static placement: {len(healthy_eps)} "
                  f"endpoint(s) hold restorable generations and must "
                  f"all come back; arm --ps_min_servers/"
                  f"--ps_max_servers to make the fleet resizable)")
            return 1
    if args.nproc is not None:
        print(f"# restorable at nproc={args.nproc}: "
              f"{len(fit_steps)} step(s); newest: "
              f"{fit_steps[-1] if fit_steps else 'NONE'}")
        # the job-level rule restore() actually applies: a HEALTHY
        # step that doesn't fit and is NEWER than the best fitting one
        # makes restore refuse (CheckpointTopologyError) rather than
        # silently fall back past it — per-step "yes" lines alone
        # would promise a restore that will not happen
        blocked = [r["step"] for r in steps
                   if r["status"] in ("ok", "legacy")
                   and r["step"] not in fit_steps]
        if blocked and (not fit_steps or max(blocked) > fit_steps[-1]):
            print(f"# WARNING: newest healthy step {max(blocked)} is "
                  f"NOT restorable at nproc={args.nproc}; restore() "
                  f"will refuse (CheckpointTopologyError) instead of "
                  f"falling back to "
                  f"{fit_steps[-1] if fit_steps else 'nothing'}")
            return 1
        if not fit_steps:
            return 1
    return 1 if bad or ps_bad else 0


if __name__ == "__main__":
    sys.exit(main())
