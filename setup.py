"""setup.py shim: builds the native runtime (.so) at install time via
a custom build step (the cmake-superbuild role, SURVEY §2.10 — the
reference compiles its C++ core during the package build; here the
same g++ invocation paddle_tpu.native uses lazily runs eagerly so the
wheel ships a prebuilt library for this platform).

`pip install .` works without a toolchain too: the native sources ship
as package data and paddle_tpu.native falls back to its import-time
fingerprint-cached build (or the documented pure-Python paths when g++
is absent).
"""

import os

from setuptools import setup
from setuptools.command.build_py import build_py


class BuildWithNative(build_py):
    def run(self):
        super().run()
        try:
            # load native/__init__.py STANDALONE (stdlib-only at import
            # time) — importing the full paddle_tpu package would need
            # jax/numpy, which a PEP 517 isolated build env lacks
            import importlib.util
            here = os.path.dirname(os.path.abspath(__file__))
            spec = importlib.util.spec_from_file_location(
                "_pt_native_build",
                os.path.join(here, "paddle_tpu", "native",
                             "__init__.py"))
            native = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(native)
            so = native._build()
            # copy the built library into the wheel's package tree
            rel = os.path.join("paddle_tpu", "native", "_build")
            dst = os.path.join(self.build_lib, rel)
            os.makedirs(dst, exist_ok=True)
            self.copy_file(so, os.path.join(dst,
                                            os.path.basename(so)))
            print(f"built native runtime: {os.path.basename(so)}")
        except Exception as e:     # no toolchain: lazy build at import
            print(f"native runtime not prebuilt ({e}); it will build "
                  f"on first import where g++ is available")


setup(cmdclass={"build_py": BuildWithNative})
