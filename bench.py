"""Benchmark: BERT-base MLM pretraining step throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = measured MFU / 0.35 (the BASELINE.json north-star MFU).
Metric format follows the reference's examples/sec convention
(ref: benchmark/fluid/fluid_benchmark.py:297-300), as tokens/sec here.
"""

import json
import os
import sys
import time

import numpy as np


class TimedResult:
    """Result of a multi-window timing run. ``dt`` is the BEST window's
    wall seconds (what throughput is computed from); ``window_dts`` are
    all window durations; ``contention_suspected`` is True when the
    window spread stayed above the threshold even after retries."""

    def __init__(self, window_dts, steps, carry, res, contention,
                 decision_spread, sub_steps=1):
        self.window_dts = window_dts
        self.dt = min(window_dts)
        # total training steps per window: timed outer calls x scanned
        # inner steps (steps_per_call), so ms_per_step reconciles with
        # the tokens/sec computed from steps*spc on the same JSON line
        self.steps = steps * sub_steps
        self.carry = carry
        self.res = res
        # the spread the contention decision was made on (best-N
        # windows) — NOT the all-windows spread, which legitimately
        # includes retried-away outliers
        self.spread = decision_spread
        self.contention_suspected = contention

    def ms_per_step(self):
        return [round(d / self.steps * 1e3, 3) for d in self.window_dts]

    def extras(self):
        """Diagnostic fields to merge into the headline JSON line (the
        anti-contention record VERDICT r3 Weak #1 asked for: per-window
        per-step ms + an explicit flag when the spread is anomalous)."""
        out = {"windows_ms_per_step": self.ms_per_step(),
               "window_spread": round(self.spread, 4)}
        if self.contention_suspected:
            out["contention_suspected"] = True
        return out


def _timed_steps(step_once, carry, steps, settle=3, windows=None,
                 spread_threshold=0.20, max_windows=6, sub_steps=1):
    """Shared timing harness for every bench mode: 1 compile/warmup
    step, ``settle`` steps to fill the dispatch pipeline, then
    ``windows`` (default 3, BENCH_WINDOWS overrides) independent timed
    windows of ``steps`` steps each. The reported time is the BEST
    window — a slow sample means interference (chip contention on the
    shared tunnel, host jitter), never a faster program, so min is the
    estimator (same reasoning as the reference's examples/sec loop
    discarding warmup, benchmark/fluid/fluid_benchmark.py:297-300, made
    robust). If the window spread exceeds ``spread_threshold``, extra
    windows run (up to ``max_windows``); if the spread over the best 3
    still exceeds it, the result carries contention_suspected=True.

    The sync is a HOST FETCH of the step's result — on the remote-PJRT
    tunnel this repo benches over, a bare block_until_ready measurably
    returned before queued dispatches executed (2 ms/step reported for
    a 166 ms/step program); fetching the value cannot lie.
    step_once(carry) -> (carry, result). Returns a TimedResult."""
    if windows is None:
        windows = int(os.environ.get("BENCH_WINDOWS", "3"))
    # >=2: a single window can neither measure spread nor flag
    # contention — exactly the silent-3x-low failure this harness exists
    # to prevent (VERDICT r3 Weak #1)
    windows = max(2, windows)
    carry, res = step_once(carry)
    float(np.ravel(np.asarray(res))[0])
    for _ in range(settle):
        carry, res = step_once(carry)
    float(np.ravel(np.asarray(res))[0])

    def one_window():
        nonlocal carry, res
        t0 = time.perf_counter()
        for _ in range(steps):
            carry, res = step_once(carry)
        float(np.ravel(np.asarray(res))[0])
        return time.perf_counter() - t0

    def best_spread(dts):
        # judge the spread on the best `windows` samples: one bad
        # window in a retried run must not flag contention if the
        # retries agree with the fast windows
        best = sorted(dts)[:windows]
        return (max(best) - min(best)) / min(best)

    dts = [one_window() for _ in range(windows)]
    while len(dts) < max_windows and best_spread(dts) > spread_threshold:
        dts.append(one_window())
    spread = best_spread(dts)
    tr = TimedResult(dts, steps, carry, res,
                     contention=spread > spread_threshold,
                     decision_spread=spread, sub_steps=sub_steps)
    # the ad-hoc windows dict also lands in the unified metrics
    # registry, so a bench run's numbers ride the same snapshot pipeline
    # as production telemetry (monitor/exporter.py; BENCH_METRICS_OUT
    # below writes the Prometheus file)
    from paddle_tpu.monitor.registry import histogram
    h = histogram("bench_window_ms_per_step",
                  "Per-step wall ms of each timed bench window")
    for v in tr.ms_per_step():
        h.observe(v)
    return tr


def _abba_overhead(window, pairs, bound=1.05, rounds=3):
    """Shared tracing-on/off A/B protocol (bench serving + dispatch):
    ABBA-ordered window quadruples — both sides of each ratio sit in
    the same slice of a shared host's drifting load — estimated by the
    TRIMMED MEAN of pair ratios (individual pairs are wide on this
    host: ~30% exceed 1.05 even for a true-1.00 effect, so a median
    over a dozen pairs flakes; the mean tightens by CLT and the trim
    guards the one wild pair). When the estimate sits above ``bound``,
    gather ``pairs`` more quadruples (all data kept, never discarded)
    up to ``rounds`` extra times — a true regression stays above the
    bound however many pairs pile on.

    ``window(traced)`` runs one timed window and returns its per-unit
    time. Returns ``(estimate, pair_ratios, on_times, off_times)``."""
    pair_ratios, on_ts, off_ts = [], [], []

    def run_pairs(n):
        for _ in range(n):
            a1 = window(True)
            b1 = window(False)
            b2 = window(False)
            a2 = window(True)
            on_ts.extend((a1, a2))
            off_ts.extend((b1, b2))
            pair_ratios.append((a1 + a2) / (b1 + b2))

    def estimate():
        rs = sorted(pair_ratios)
        if len(rs) >= 6:
            rs = rs[1:-1]
        return float(np.mean(rs))

    run_pairs(pairs)
    for _round in range(rounds):
        if estimate() < bound:
            break
        run_pairs(pairs)
    return estimate(), pair_ratios, on_ts, off_ts


def bench_resnet50():
    """Secondary benchmark (`python bench.py resnet50`): ResNet-50
    images/sec/chip + MFU — BASELINE.json's second headline config."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import resnet
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh, set_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # BENCH_RESNET_REMAT=block A/Bs the conv-outputs-only remat
    # experiment (models/resnet.py ResNetConfig.remat; BASELINE.md
    # "ResNet-50 remat experiment")
    rm = os.environ.get("BENCH_RESNET_REMAT", "none")
    assert rm in ("none", "block"), \
        f"BENCH_RESNET_REMAT must be none|block, got {rm!r}"
    cfg = (resnet.resnet50(remat=rm) if on_tpu
           else resnet.resnet_cifar10(depth=8, image_size=16, remat=rm))
    batch = 256 if on_tpu else 8
    steps = 20 if on_tpu else 3
    mesh = set_mesh(make_mesh(MeshConfig(data=1), devices=jax.devices()[:1]))
    opt = pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9)
    # scanned steps per dispatch (train_from_dataset pattern) amortize
    # the ~7 ms remote-PJRT dispatch gap; the batch is reused per inner
    # step exactly like the reference's --use_fake_data. r3 A/B on-chip:
    # spc=8 2,568 img/s vs spc=4 2,545 (BENCH_SPC overrides)
    spc = int(os.environ.get("BENCH_SPC", "8" if on_tpu else "1"))
    init_fn, step_fn = resnet.make_train_step(cfg, opt, mesh,
                                              steps_per_call=spc)
    imgs, labels = resnet.synthetic_batch(cfg, batch)
    # pre-stage the batch on device: the measured loop models an input
    # pipeline that overlaps host->device transfer (ref: buffered_reader.cc)
    from jax.sharding import NamedSharding, PartitionSpec as P
    dsh = NamedSharding(mesh, P("data"))
    imgs = jax.device_put(imgs, dsh)
    labels = jax.device_put(labels, dsh)
    params, opt_state = init_fn(jax.random.PRNGKey(0))

    def once(carry):
        params, opt_state = carry
        loss, acc, params, opt_state = step_fn(params, opt_state, imgs,
                                               labels)
        return (params, opt_state), loss

    tr = _timed_steps(once, (params, opt_state), steps, sub_steps=spc)
    loss = tr.res
    img_per_sec = batch * spc * steps / tr.dt
    peak = 197e12
    mfu = img_per_sec * resnet.flops_per_image(cfg) / peak
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(mfu / 0.35, 4),
        **tr.extras(),
    }))
    print(f"# device={dev.platform} batch={batch} steps={steps} "
          f"loss={float(loss):.4f} mfu={mfu:.3f}", file=sys.stderr)


def bench_inference():
    """`python bench.py inference` — the reference's OWN headline
    benchmark shape: ResNet50/VGG16 imagenet single-image-stream
    inference latency, half precision (bf16 here, fp16 there) vs fp32,
    per batch size (ref: paddle/contrib/float16/float16_benchmark.md;
    tables carried in BASELINE.md). One JSON line per (model, dtype, mb);
    vs_baseline on the summary line = reference V100 fp16 latency /
    ours at the largest common batch."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.models import resnet, vgg

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    steps = 30 if on_tpu else 3
    # reference table rows: (model tag, cfg factory, batches, V100 fp16
    # latency at largest batch — float16_benchmark.md:23-25,39-44)
    jobs = [
        ("resnet50", lambda dt: resnet.resnet50(dtype=dt),
         resnet, [1, 2, 4, 8, 16, 32, 64, 128] if on_tpu else [1, 2],
         64.52),
        ("vgg16", lambda dt: vgg.vgg16(dtype=dt),
         vgg, [1, 2, 4, 8, 16, 32, 64] if on_tpu else [1, 2], 60.23),
    ]
    summary = {}
    for tag, mk, mod, batches, ref_ms in jobs:
        for dtname, dt in (("bf16", jnp.bfloat16), ("fp32", jnp.float32)):
            cfg = mk(dt)
            if not on_tpu:
                cfg = (resnet.resnet_cifar10(depth=8, image_size=16,
                                             dtype=dt)
                       if mod is resnet else vgg.vgg11(image_size=32,
                                                       dtype=dt))
            params = mod.init_params(jax.random.PRNGKey(0), cfg)
            fwd = jax.jit(
                lambda p, x, cfg=cfg, mod=mod: mod.forward(
                    p, cfg, x, train=False))
            for mb in batches:
                x = jnp.zeros((mb, cfg.image_size, cfg.image_size, 3),
                              jnp.float32)

                def once(carry):
                    out = fwd(params, x)
                    return carry, jax.tree.leaves(out)[0].ravel()[:1]

                tr = _timed_steps(once, None, steps, settle=0)
                ms = tr.dt / steps * 1e3
                line = {
                    "metric": f"{tag}_{dtname}_infer_latency_mb{mb}",
                    "value": round(ms, 3), "unit": "ms"}
                if tr.contention_suspected:
                    line["contention_suspected"] = True
                print(json.dumps(line))
                summary[(tag, dtname, mb)] = (ms, tr.contention_suspected)
    if on_tpu:
        # distinct metric names: the per-batch loop already printed the
        # raw latencies; these summarize vs the reference's V100 fp16
        # numbers at each model's largest common batch (jobs[..].ref_ms)
        for tag, mk, mod, batches, ref_ms in jobs:
            entry = summary.get((tag, "bf16", batches[-1]))
            if entry:
                ours, contended = entry
                line = {
                    "metric": (f"{tag}_bf16_infer_speedup_vs_v100fp16_"
                               f"mb{batches[-1]}"),
                    "value": round(ref_ms / ours, 3), "unit": "x",
                    "vs_baseline": round(ref_ms / ours, 3)}
                if contended:
                    line["contention_suspected"] = True
                print(json.dumps(line))


def bench_int8():
    """`python bench.py int8` — int8 vs bf16 inference latency on the
    chip (VERDICT r4 #2; the reference's int8 story is perf-motivated:
    trt int8 engine + calibrator, inference/tensorrt/engine.h:43,
    trt_int8_calibrator.cc, measured with the float16_benchmark.md
    discipline). Three model shapes at 2-3 batch sizes each:

      mlp        — digits-style fc stack (quantized_mul)
      resnet50   — the three dominant ResNet-50 conv shapes chained
                   (quantized_conv2d)
      bert_layer — one BERT-base encoder layer's matmuls at S=128
                   (quantized_mul for QKV/proj/FFN)

    Each row prints int8 ms, bf16 ms, and speedup; v5e's MXU runs
    s8xs8->s32 at 2x the bf16 rate (394 vs 197 TOPS peak), so a row
    materially above 1.0x means XLA mapped the dot/conv onto int8 MXU
    passes; below 1.0x means the quantize/dequantize elementwise
    traffic dominates at that shape (an honest negative, recorded)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.quantize import (quantize_linear, quantized_conv2d,
                                         quantized_mul)

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    steps = 30 if on_tpu else 3
    rng = np.random.RandomState(0)

    # Each candidate fn(*args, jit_c) -> scalar runs ITERS times
    # inside ONE jitted fori_loop (the scalar carry perturbs the input
    # so iterations cannot be CSE'd): at these shapes a single
    # application is ~0.1 ms of device time against the ~4-5 ms
    # remote-PJRT dispatch floor, which would swamp any int8-vs-bf16
    # difference. Reported ms is per INNER iteration.
    ITERS = 100 if on_tpu else 2   # CPU smoke: the loop exists to
    # amortize the TPU tunnel; on CPU 100 conv iterations would take
    # minutes and measure nothing

    def timed(fn, *args):
        def looped(*a):
            def body(i, c):
                return fn(*a, c * 1e-12)
            return jax.lax.fori_loop(0, ITERS, body, jnp.float32(0.0))

        jfn = jax.jit(looped)

        def once(carry):
            return carry, jnp.ravel(jfn(*args))[:1]

        tr = _timed_steps(once, None, steps, settle=2)
        return tr.dt / steps / ITERS * 1e3, tr.contention_suspected

    def report(tag, mb, int8_ms, bf16_ms, contended):
        line = {"metric": f"int8_{tag}_mb{mb}_speedup_vs_bf16",
                "value": round(bf16_ms / int8_ms, 3), "unit": "x",
                "int8_ms": round(int8_ms, 3),
                "bf16_ms": round(bf16_ms, 3)}
        if contended:
            line["contention_suspected"] = True
        print(json.dumps(line))

    # -- mlp: 784 -> 512 -> 512 -> 10 (digits-style, scaled up) ----------
    dims = [784, 512, 512, 10]
    ws = [rng.randn(a, b).astype(np.float32) * 0.05
          for a, b in zip(dims, dims[1:])]
    w_scales = [float(np.abs(w).max()) for w in ws]
    wq = [np.asarray(quantize_linear(w, s)) for w, s in zip(ws, w_scales)]
    wb = [jnp.asarray(w, jnp.bfloat16) for w in ws]

    def mlp_int8(x, c):
        h = x + c
        for q, s in zip(wq, w_scales):
            h = jnp.maximum(quantized_mul(h, q, 4.0, s), 0.0)
        return h.sum()

    def mlp_bf16(x, c):
        h = (x + c).astype(jnp.bfloat16)
        for w in wb:
            h = jnp.maximum(h @ w, 0.0)
        return h.sum(dtype=jnp.float32)

    for mb in ([64, 512, 4096] if on_tpu else [8]):
        x = jnp.asarray(rng.rand(mb, dims[0]).astype(np.float32))
        i_ms, c1 = timed(mlp_int8, x)
        b_ms, c2 = timed(mlp_bf16, x)
        report("mlp", mb, i_ms, b_ms, c1 or c2)

    # -- resnet50 conv shapes: the three layer archetypes chained --------
    # (1x1 expand, 3x3 mid-stage, 1x1 reduce — where ResNet-50's conv
    # FLOPs live; chaining keeps intermediate activations on device)
    conv_shapes = [  # (cin, cout, k, hw, stride)
        (256, 64, 1, 56, 1),
        (128, 128, 3, 28, 1),
        (1024, 256, 1, 14, 1),
    ]
    cw = [rng.randn(co, ci, k, k).astype(np.float32) * 0.05
          for ci, co, k, hw, st in conv_shapes]
    cw_scales = [float(np.abs(w).max()) for w in cw]
    cwq = [np.asarray(quantize_linear(w, s))
           for w, s in zip(cw, cw_scales)]
    cwb = [jnp.asarray(w, jnp.bfloat16) for w in cw]

    def convs_int8(*xs_c):
        *xs, c = xs_c
        out = jnp.float32(0.0)
        for x, q, s, (ci, co, k, hw, st) in zip(xs, cwq, cw_scales,
                                                conv_shapes):
            out += quantized_conv2d(x + c, q, 4.0, s, stride=st,
                                    padding=k // 2).sum()
        return out

    def convs_bf16(*xs_c):
        *xs, c = xs_c
        out = jnp.float32(0.0)
        for x, w, (ci, co, k, hw, st) in zip(xs, cwb, conv_shapes):
            dn = jax.lax.conv_dimension_numbers(
                x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
            out += jax.lax.conv_general_dilated(
                (x + c).astype(jnp.bfloat16), w, (st, st),
                [(k // 2, k // 2)] * 2,
                dimension_numbers=dn).sum(dtype=jnp.float32)
        return out

    for mb in ([8, 32, 128] if on_tpu else [2]):
        xs = [jnp.asarray(rng.rand(mb, ci, hw, hw).astype(np.float32))
              for ci, co, k, hw, st in conv_shapes]
        i_ms, c1 = timed(convs_int8, *xs)
        b_ms, c2 = timed(convs_bf16, *xs)
        report("resnet50convs", mb, i_ms, b_ms, c1 or c2)

    # -- bert encoder layer matmuls (h=768, ffn=3072, S=128) -------------
    H, F, S = 768, 3072, 128
    bw = {"qkv": rng.randn(H, 3 * H), "proj": rng.randn(H, H),
          "up": rng.randn(H, F), "down": rng.randn(F, H)}
    bw = {k: (v * 0.02).astype(np.float32) for k, v in bw.items()}
    b_scales = {k: float(np.abs(v).max()) for k, v in bw.items()}
    bq = {k: np.asarray(quantize_linear(v, b_scales[k]))
          for k, v in bw.items()}
    bb = {k: jnp.asarray(v, jnp.bfloat16) for k, v in bw.items()}

    def bert_int8(x, c):
        qkv = quantized_mul(x + c, bq["qkv"], 8.0, b_scales["qkv"],
                            x_num_col_dims=2)
        h = quantized_mul(qkv[..., :H], bq["proj"], 8.0,
                          b_scales["proj"], x_num_col_dims=2)
        u = jnp.maximum(quantized_mul(h, bq["up"], 8.0, b_scales["up"],
                                      x_num_col_dims=2), 0.0)
        return quantized_mul(u, bq["down"], 8.0, b_scales["down"],
                             x_num_col_dims=2).sum()

    def bert_bf16(x, c):
        xb = (x + c).astype(jnp.bfloat16)
        qkv = xb @ bb["qkv"]
        h = qkv[..., :H] @ bb["proj"]
        u = jnp.maximum(h @ bb["up"], 0)
        return (u @ bb["down"]).sum(dtype=jnp.float32)

    for mb in ([8, 32] if on_tpu else [2]):
        x = jnp.asarray(rng.rand(mb, S, H).astype(np.float32))
        i_ms, c1 = timed(bert_int8, x)
        b_ms, c2 = timed(bert_bf16, x)
        report("bert_layer", mb, i_ms, b_ms, c1 or c2)


def _passes_trunk_program(hidden, seq, blocks):
    """Static-graph BERT trunk for `bench.py passes` (the pass pipeline
    operates on Programs; models/bert.py is functional): ``blocks``
    post-LN transformer blocks of fc-projected attention + fc FFN —
    mul+bias(+act) chains (FuseMatmulBiasActPass fodder, the
    reference's fc_fuse_pass shape), the 1/sqrt(d) attention scale
    (scale-chain family) and the k-transpose (transpose/reshape
    family). Returns (main, startup, fetch_name)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name

    pt.enable_static()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), unique_name.guard():
        x = pt.static.data("x", [seq, hidden], dtype="float32")
        for _ in range(blocks):
            q = layers.fc(x, hidden, num_flatten_dims=2)
            k = layers.fc(x, hidden, num_flatten_dims=2)
            v = layers.fc(x, hidden, num_flatten_dims=2)
            kt = layers.transpose(k, [0, 2, 1])
            att = layers.matmul(q, kt)
            att = layers.scale(att, scale=1.0 / np.sqrt(hidden))
            att = layers.softmax(att)
            ctx = layers.matmul(att, v)
            o = layers.fc(ctx, hidden, num_flatten_dims=2)
            x = layers.layer_norm(layers.elementwise_add(x, o),
                                  begin_norm_axis=2)
            h = layers.fc(x, 4 * hidden, act="relu",
                          num_flatten_dims=2)
            h = layers.fc(h, hidden, num_flatten_dims=2)
            x = layers.layer_norm(layers.elementwise_add(x, h),
                                  begin_norm_axis=2)
        out = layers.mean(x)
    return main, startup, out.name


def _passes_mlp_program():
    """The serving MLP (same shape as ``_freeze_serving_mlp``) as a
    bare program, for the `bench.py passes` A/B."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name

    pt.enable_static()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), unique_name.guard():
        x = pt.static.data("x", [256], dtype="float32")
        h = layers.fc(x, 256, act="relu")
        h = layers.fc(h, 256, act="relu")
        out = layers.fc(h, 10)
        out = layers.mean(out)
    return main, startup, out.name


def bench_passes():
    """`python bench.py passes` — the program-level pass pipeline's
    on/off A/B (docs/PERFORMANCE.md "Program pass pipeline"): the SAME
    program runs through the Executor twice, wrapped in
    ``CompiledProgram``s whose ``BuildStrategy.apply_ir_passes`` pins
    the pipeline on vs off (off = the bit-identical legacy lowering),
    over the static BERT trunk and the serving MLP. Windows interleave
    in ABBA quadruples (the shared ``_abba_overhead`` protocol) so both
    sides of each ratio see the same slice of host drift; one JSON line
    per model carries the step-time ratio, the per-pass ops-removed
    evidence (``PipelineReport``; the live compile also lands
    ``program_pass_*`` in the registry snapshot) and an
    ``outputs_match`` fetch-equivalence check. Headline
    ``passes_step_ratio`` is the WORST model ratio — the acceptance
    bar is <= 1.0x (the pipeline must never cost a step). Knobs:
    BENCH_PASSES_STEPS / BENCH_PASSES_PAIRS."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.compiler import BuildStrategy, CompiledProgram
    from paddle_tpu.static import opt_passes

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    steps = int(os.environ.get("BENCH_PASSES_STEPS",
                               "30" if on_tpu else "6"))
    pairs = int(os.environ.get("BENCH_PASSES_PAIRS", "3"))
    rng = np.random.RandomState(0)

    models = []
    main, startup, fetch = _passes_mlp_program()
    models.append(("serving_mlp", main, startup,
                   {"x": rng.rand(8, 256).astype(np.float32)}, fetch))
    h, s, b = (256, 128, 4) if on_tpu else (32, 16, 2)
    main, startup, fetch = _passes_trunk_program(h, s, b)
    models.append(("bert_trunk", main, startup,
                   {"x": rng.rand(8 if on_tpu else 2, s, h)
                    .astype(np.float32)}, fetch))

    worst = None
    for tag, main, startup, feed, fetch in models:
        scope = pt.static.Scope()
        with pt.static.scope_guard(scope):
            exe = pt.Executor()
            exe.run(startup)
            bs_on, bs_off = BuildStrategy(), BuildStrategy()
            bs_on.apply_ir_passes = True
            bs_off.apply_ir_passes = False
            prog_on = CompiledProgram(main, build_strategy=bs_on)
            prog_off = CompiledProgram(main, build_strategy=bs_off)

            def run_once(prog, feed=feed, fetch=fetch, exe=exe):
                return np.asarray(
                    exe.run(prog, feed=feed, fetch_list=[fetch])[0])

            # the live compile runs under FLAGS_pass_cost_evidence, so
            # each pass's predicted FLOPs/bytes delta (pre/post HLO
            # cost_analysis) lands in program_pass_*_delta and the
            # pass_evidence table — probing happens at compile time
            # only, the timed windows below never pay it
            from paddle_tpu.core.flags import set_flags
            from paddle_tpu.monitor import cost as _pcost
            ev0 = _pcost.pass_evidence()
            set_flags({"pass_cost_evidence": True})
            try:
                out_on = run_once(prog_on)  # compiles each path once
            finally:
                set_flags({"pass_cost_evidence": False})
            predicted = {
                p: {k: t.get(k, 0.0) - ev0.get(p, {}).get(k, 0.0)
                    for k in ("flops_delta", "bytes_delta")}
                for p, t in _pcost.pass_evidence().items()
                if "flops_delta" in t or "bytes_delta" in t}
            out_off = run_once(prog_off)
            outputs_match = bool(np.allclose(out_on, out_off,
                                             rtol=1e-5, atol=1e-6))

            def window(on, prog_on=prog_on, prog_off=prog_off,
                       run_once=run_once):
                prog = prog_on if on else prog_off
                t0 = time.perf_counter()
                for _ in range(steps):
                    r = run_once(prog)
                float(np.ravel(r)[0])
                return (time.perf_counter() - t0) / steps * 1e3

            window(True), window(False)     # settle both paths
            est, pair_ratios, on_ms, off_ms = _abba_overhead(
                window, pairs, bound=1.0)
        # evidence from a metrics-silent re-run of the pipeline (the
        # live compile above already published program_pass_* to the
        # registry; this report is the per-model JSON the smoke reads)
        _, report = opt_passes.optimize_program(
            main, targets=(fetch,), record=False)
        print(json.dumps({
            "metric": f"passes_step_ratio_{tag}",
            "value": round(est, 4), "unit": "x",
            "on_ms_per_step": round(float(np.median(on_ms)), 3),
            "off_ms_per_step": round(float(np.median(off_ms)), 3),
            "pair_ratios": [round(r, 4) for r in pair_ratios],
            "outputs_match": outputs_match,
            "steps_per_window": steps,
            "pass_cost_deltas": {
                p: {k: round(float(v), 1) for k, v in d.items()}
                for p, d in sorted(predicted.items())},
            **report.as_dict(),
        }))
        if worst is None or est > worst:
            worst = est
    print(json.dumps({
        "metric": "passes_step_ratio",
        "value": round(worst, 4), "unit": "x",
        # bigger-is-better convention: legacy/optimized step speedup
        "vs_baseline": round(1.0 / worst, 4),
    }))


def _freeze_serving_mlp(dirname, quant_dir=None, quant_mode="int8"):
    """The serving-bench model: a dispatch-bound MLP — online serving
    of small models is dominated by per-request dispatch overhead,
    exactly the cost continuous batching amortizes (a compute-bound
    model would measure the chip, not the serving stack). Shared by
    the headline A/B, the chaos bench, and the hot-swap bench (which
    freezes a SECOND copy as the new version). ``quant_dir``
    additionally freezes THE SAME weights there with an
    ``export_aot(quantize=quant_mode)`` sidecar — the quantized side
    of the BENCH_SERVING_QUANT A/B (same-weights is what makes its
    accuracy delta meaningful)."""
    import paddle_tpu as pt
    from paddle_tpu import layers
    from paddle_tpu.framework import unique_name

    pt.enable_static()
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), unique_name.guard():
        x = pt.static.data("x", [256], dtype="float32")
        h = layers.fc(x, 256, act="relu")
        h = layers.fc(h, 256, act="relu")
        out = layers.fc(h, 10)
    scope = pt.static.Scope()
    with pt.static.scope_guard(scope):
        exe = pt.Executor()
        exe.run(startup)
        pt.io.save_inference_model(dirname, ["x"], [out], exe,
                                   main_program=main)
        if quant_dir is not None:
            from paddle_tpu import inference as inf
            pt.io.save_inference_model(quant_dir, ["x"], [out], exe,
                                       main_program=main)
            inf.export_aot(quant_dir, main, ["x"], [out.name], scope,
                           [{"x": ((1, 256), "float32")}],
                           quantize=quant_mode)
    return dirname


def _bench_serving_swap(d, feed, max_batch, max_wait_ms):
    """The hot-swap half of `bench.py serving`
    (BENCH_SERVING_SWAP=1, docs/SERVING.md "Hot model swap"): ONE
    open-loop Poisson schedule at ~0.5x measured capacity with a
    ``server.swap()`` to a freshly frozen second version fired at the
    schedule midpoint. Every request is accounted (a hang is a bench
    failure); two JSON lines:

    - ``serving_swap_p99_ratio``: p99 latency of requests whose
      [arrival, completion] overlaps the swap window (gate ->
      watchdog-pass) vs the p99 of the rest — the acceptance target
      is <= 1.5x (the swap builds the standby OFF the serving path,
      so overlap requests should barely notice).
    - ``serving_swap_blip_ms``: the longest gap between consecutive
      request completions that overlaps the swap window — the cutover
      stall an operator would see on a completions dashboard.

    Knobs: BENCH_SERVING_SWAP_REQS (default 300),
    BENCH_SERVING_SWAP_WATCHDOG_MS (default 200)."""
    import tempfile
    import threading

    from paddle_tpu.serving import InferenceServer, ServingConfig

    n = int(os.environ.get("BENCH_SERVING_SWAP_REQS", "300"))
    watchdog_ms = float(os.environ.get(
        "BENCH_SERVING_SWAP_WATCHDOG_MS", "200"))
    d2 = _freeze_serving_mlp(tempfile.mkdtemp())

    srv = InferenceServer(d, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=n + 64, replicas=1))
    t0 = time.perf_counter()
    for _ in range(20):
        srv.infer({"x": feed}, timeout=60)
    cap = 20 / (time.perf_counter() - t0)
    offered = 0.5 * cap
    sched = np.cumsum(np.random.RandomState(17).exponential(
        1.0 / offered, size=n))

    swap_state = {}

    def do_swap():
        t_s = time.perf_counter()
        try:
            swap_state["report"] = srv.swap(d2,
                                            watchdog_ms=watchdog_ms)
        except Exception as e:       # surfaced in the JSON row
            swap_state["error"] = f"{type(e).__name__}: {e}"
        swap_state["t0"] = t_s
        swap_state["t1"] = time.perf_counter()

    pend = [None] * n
    arrived = [0.0] * n
    swap_thread = None
    t_origin = time.perf_counter()
    for i in range(n):
        dly = t_origin + sched[i] - time.perf_counter()
        if dly > 0:
            time.sleep(dly)
        if i == n // 2 and swap_thread is None:
            swap_thread = threading.Thread(target=do_swap,
                                           daemon=True)
            swap_thread.start()
        arrived[i] = t_origin + sched[i]
        pend[i] = srv.submit({"x": feed})
    hangs = 0
    for p in pend:
        try:
            p.result(timeout=120)
        except TimeoutError:
            hangs += 1
        except Exception:
            pass                     # typed errors are accounted below
    if swap_thread is not None:
        swap_thread.join(120)
    srv.close(timeout=60)

    t0s = swap_state.get("t0", float("inf"))
    t1s = swap_state.get("t1", float("-inf"))
    done = [p.t_done for p in pend]
    lat_ms = [(dn - ar) * 1e3 for dn, ar in zip(done, arrived)
              if dn is not None]
    overlap = [(dn - ar) * 1e3 for dn, ar in zip(done, arrived)
               if dn is not None and ar <= t1s and dn >= t0s]
    steady = [(dn - ar) * 1e3 for dn, ar in zip(done, arrived)
              if dn is not None and (ar > t1s or dn < t0s)]
    p99_overlap = (float(np.percentile(overlap, 99))
                   if overlap else None)
    p99_steady = (float(np.percentile(steady, 99))
                  if steady else None)
    ratio = (round(p99_overlap / p99_steady, 3)
             if overlap and steady and p99_steady > 0 else None)
    # the longest completion silence overlapping the swap window: the
    # stall an operator's completions-per-second dashboard would show
    comp = sorted(dn for dn in done if dn is not None)
    blip = 0.0
    for a, b in zip(comp, comp[1:]):
        if b >= t0s and a <= t1s:
            blip = max(blip, (b - a) * 1e3)
    print(json.dumps({
        "metric": "serving_swap_p99_ratio",
        "value": ratio, "unit": "x",
        "p99_overlap_ms": (round(p99_overlap, 2)
                           if p99_overlap is not None else None),
        "p99_steady_ms": (round(p99_steady, 2)
                          if p99_steady is not None else None),
        "n_overlap": len(overlap), "n_steady": len(steady),
        "hangs": hangs,
        "outcome": (swap_state.get("report", {}).get("outcome")
                    if "report" in swap_state
                    else swap_state.get("error", "not-run")),
        "swap_ms": (round((t1s - t0s) * 1e3, 1)
                    if "t0" in swap_state else None),
        "offered_qps": round(offered, 1),
    }))
    print(json.dumps({
        "metric": "serving_swap_blip_ms",
        "value": round(blip, 2), "unit": "ms",
        "swap_window_ms": (round((t1s - t0s) * 1e3, 1)
                           if "t0" in swap_state else None),
        "watchdog_ms": watchdog_ms,
    }))


def _bench_serving_quant(max_batch, max_wait_ms):
    """The quantized-serving half of `bench.py serving`
    (BENCH_SERVING_QUANT=1, docs/SERVING.md "Quantized serving"):
    fp32 vs weight-quantized serving of THE SAME weights under the
    SAME open-loop Poisson schedule. Two ``InferenceServer``s boot
    from two frozen dirs sharing one init (``_freeze_serving_mlp``'s
    quant_dir); the quantized dir carries the
    ``export_aot(quantize=...)`` sidecar the warm boot loads
    transparently. JSON rows: per-system sustained QPS + p50/p99 +
    device-resident param bytes (``ReplicaPool.resident_param_bytes``),
    the QPS ratio (acceptance: >= 1.0x — weight-only PTQ must never
    cost throughput), the resident-bytes ratio (acceptance: <= 0.55x
    for int8) and the fixture accuracy delta (max |quant - fp| over
    the fp output span on a 16-row fixture batch — the documented
    accuracy evidence). Knobs: BENCH_SERVING_QUANT_REQS / _MODE,
    BENCH_SERVING_REPLICAS / _RATE_X."""
    import tempfile

    from paddle_tpu.serving import InferenceServer, ServingConfig

    mode = os.environ.get("BENCH_SERVING_QUANT_MODE", "int8")
    n = int(os.environ.get("BENCH_SERVING_QUANT_REQS", "400"))
    rate_x = float(os.environ.get("BENCH_SERVING_RATE_X", "3.0"))
    replicas = int(os.environ.get("BENCH_SERVING_REPLICAS", "1"))

    d_fp = tempfile.mkdtemp()
    d_q = tempfile.mkdtemp()
    _freeze_serving_mlp(d_fp, quant_dir=d_q, quant_mode=mode)
    rng = np.random.RandomState(0)
    feed = rng.rand(1, 256).astype(np.float32)
    fixture = rng.rand(16, 256).astype(np.float32)

    results = {}
    sched = offered = None
    for tag, d in (("fp", d_fp), ("quant", d_q)):
        srv = InferenceServer(d, ServingConfig(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            max_queue=n + 64, replicas=replicas))
        # fixture rides in bucket-ladder-sized chunks (a single
        # 16-row request would overflow a small max_batch)
        chunk = max(1, min(max_batch, len(fixture)))
        fix_out = np.vstack([
            np.asarray(srv.infer({"x": fixture[i:i + chunk]},
                                 timeout=120)[0])
            for i in range(0, len(fixture), chunk)])
        t0 = time.perf_counter()
        for _ in range(20):
            srv.infer({"x": feed}, timeout=60)
        svc_s = (time.perf_counter() - t0) / 20
        if sched is None:
            # ONE schedule, derived from the FP service rate, shared
            # by both systems — equal offered load is literal
            offered = rate_x * replicas / svc_s
            sched = np.cumsum(np.random.RandomState(42).exponential(
                1.0 / offered, size=n))
        pend = [None] * n
        arrived = [0.0] * n
        t_origin = time.perf_counter()
        for i in range(n):
            dly = t_origin + sched[i] - time.perf_counter()
            if dly > 0:
                time.sleep(dly)
            arrived[i] = t_origin + sched[i]
            pend[i] = srv.submit({"x": feed})
        for p in pend:
            p.result(timeout=600)
        done = [p.t_done for p in pend]
        lat_ms = np.sort((np.asarray(done) - np.asarray(arrived))
                         * 1e3)
        qps = n / (max(done) - t_origin)
        param_bytes = srv.pool.resident_param_bytes()
        srv.close(timeout=60)
        results[tag] = {"qps": qps, "bytes": param_bytes,
                        "out": fix_out}
        row = {
            "metric": f"serving_{tag}_qps",
            "value": round(qps, 1), "unit": "req/s",
            "offered_qps": round(offered, 1), "n_requests": n,
            "replicas": replicas,
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
            "param_bytes": int(param_bytes),
            "service_ms": round(svc_s * 1e3, 3),
        }
        if tag == "quant":
            row["quantize"] = mode
        print(json.dumps(row))

    span = float(np.max(np.abs(results["fp"]["out"]))) + 1e-9
    delta = float(np.max(np.abs(results["quant"]["out"]
                                - results["fp"]["out"]))) / span
    print(json.dumps({
        "metric": "serving_quant_vs_fp_qps",
        "value": round(results["quant"]["qps"]
                       / results["fp"]["qps"], 3),
        "unit": "x",
        "vs_baseline": round(results["quant"]["qps"]
                             / results["fp"]["qps"], 3),
        "quantize": mode,
    }))
    print(json.dumps({
        "metric": "serving_quant_param_bytes_ratio",
        "value": round(results["quant"]["bytes"]
                       / results["fp"]["bytes"], 4),
        "unit": "x",
        "fp_bytes": int(results["fp"]["bytes"]),
        "quant_bytes": int(results["quant"]["bytes"]),
    }))
    print(json.dumps({
        "metric": "serving_quant_accuracy_delta",
        "value": round(delta, 6), "unit": "rel",
        "fixture_rows": int(fixture.shape[0]),
        "fp_output_span": round(span, 4),
        "quantize": mode,
    }))


def _bench_serving_http(d, feed, max_batch, max_wait_ms, replicas):
    """The front-door half of `bench.py serving`
    (BENCH_SERVING_HTTP=1, docs/SERVING.md "Front door"): ONE
    deterministic open-loop Poisson schedule, run through the wire
    (persistent ``WireClient`` connections against a live
    ``HttpFrontDoor``) and in-process (``srv.submit``), interleaved in
    ABBA quadruples via the shared ``_abba_overhead`` protocol so both
    sides see the same slice of host drift. Emits
    ``serving_http_vs_inproc_p99_ratio`` — the wire path's tail cost
    over the library path (JSON + socket + handler thread per
    request; no bound asserted, the number IS the evidence). Offered
    load is half the measured closed-loop capacity, so both windows
    measure overhead rather than saturation queueing. Knobs:
    BENCH_SERVING_HTTP_REQS (default 80), _PAIRS (default 2), _CONNS
    (default 8 client connections)."""
    import queue as _queue
    import threading

    from paddle_tpu.serving import (
        FrontDoorConfig, HttpFrontDoor, InferenceServer,
        ServingConfig, WireClient,
    )

    n = int(os.environ.get("BENCH_SERVING_HTTP_REQS", "80"))
    pairs = int(os.environ.get("BENCH_SERVING_HTTP_PAIRS", "2"))
    conns = int(os.environ.get("BENCH_SERVING_HTTP_CONNS", "8"))

    srv = InferenceServer(d, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=4 * n + conns, replicas=replicas))
    door = HttpFrontDoor(srv, FrontDoorConfig()).start()
    try:
        np.asarray(srv.infer({"x": feed}, timeout=120)[0])
        with WireClient("127.0.0.1", door.port) as warm:
            st, _, _ = warm.infer({"x": feed})
            assert st == 200, f"warm wire request failed: {st}"

        t0 = time.perf_counter()
        for _ in range(20):
            srv.infer({"x": feed}, timeout=60)
        cap = 20 / (time.perf_counter() - t0)
        offered = 0.5 * cap
        sched = np.cumsum(np.random.RandomState(42).exponential(
            1.0 / offered, size=n))

        def open_loop(submit):
            t_origin = time.perf_counter()
            for i in range(n):
                dly = t_origin + sched[i] - time.perf_counter()
                if dly > 0:
                    time.sleep(dly)
                submit(i, t_origin + sched[i])
            return t_origin

        def window_inproc():
            pend, arrived = [None] * n, [0.0] * n
            open_loop(lambda i, ta: (
                arrived.__setitem__(i, ta),
                pend.__setitem__(i, srv.submit({"x": feed}))))
            for p in pend:
                p.result(timeout=600)
            lat = [(p.t_done - ta) * 1e3
                   for p, ta in zip(pend, arrived)]
            return float(np.percentile(lat, 99))

        def window_wire():
            work = _queue.Queue()
            lat = [None] * n
            errs = []

            def client_worker():
                c = WireClient("127.0.0.1", door.port)
                try:
                    while True:
                        item = work.get()
                        if item is None:
                            return
                        i, ta = item
                        status, _h, _p = c.infer({"x": feed})
                        if status != 200:
                            errs.append((i, status))
                        lat[i] = (time.perf_counter() - ta) * 1e3
                except Exception as e:          # pragma: no cover
                    errs.append(e)
                finally:
                    c.close()

            threads = [threading.Thread(target=client_worker,
                                        daemon=True)
                       for _ in range(conns)]
            for t in threads:
                t.start()
            open_loop(lambda i, ta: work.put((i, ta)))
            for _ in threads:
                work.put(None)
            for t in threads:
                t.join(600)
            # every request accounted: a silent drop would flatter
            # the wire tail exactly where it hurts
            assert not errs and all(v is not None for v in lat), \
                f"wire window failures: {errs[:3]}"
            return float(np.percentile(lat, 99))

        def window(wire):
            return window_wire() if wire else window_inproc()

        window(True), window(False)             # settle both paths
        est, pair_ratios, wire_p99, inproc_p99 = _abba_overhead(
            window, pairs, bound=float("inf"), rounds=0)
        print(json.dumps({
            "metric": "serving_http_vs_inproc_p99_ratio",
            "value": round(est, 3), "unit": "x",
            "http_p99_ms": round(float(np.median(wire_p99)), 2),
            "inproc_p99_ms": round(float(np.median(inproc_p99)), 2),
            "pair_ratios": [round(r, 3) for r in pair_ratios],
            "n_per_window": n, "client_conns": conns,
            "offered_qps": round(offered, 1),
        }))
    finally:
        door.stop()
        srv.close(timeout=60)


def bench_serving():
    """`python bench.py serving` — OPEN-LOOP serving load (the honest
    way to measure tail latency: arrivals follow a deterministic-seed
    Poisson schedule at a target offered rate, and a request's latency
    is measured from its SCHEDULED arrival — a saturated system cannot
    hide queueing by slowing the load generator, i.e. no coordinated
    omission). Two systems take the SAME arrival schedule:

      baseline — single-request dispatch: ``replicas`` worker threads,
                 each with a ``Predictor.clone()``, draining one queue
                 one request at a time (the pre-serving-subsystem
                 shape);
      server   — ``paddle_tpu.serving.InferenceServer`` with the same
                 replica count: continuous micro-batching over
                 per-bucket AOT executables (docs/SERVING.md).

    The offered rate is ``BENCH_SERVING_RATE_X`` (default 3.0) times
    the measured single-request service rate — deliberately past the
    baseline's capacity, where batching either pays or doesn't. One
    JSON line per system with sustained QPS, offered QPS, p50/p99 ms,
    and (server) the micro-batch fill ratio, plus a ratio line.
    Knobs: BENCH_SERVING_REQS / _REPLICAS / _MAX_BATCH / _RATE_X /
    _MAX_WAIT_MS. The ``serving_*`` registry metrics land in the
    end-of-run snapshot every bench mode emits.

    ``BENCH_SERVING_CHAOS=1`` runs the RESILIENCE bench instead
    (docs/SERVING.md "Resilience"): a 2-replica clean-vs-stall A/B
    emitting ``serving_chaos_p99_ratio`` (p99 of unaffected requests
    with one replica wedged mid-load vs the clean run),
    ``serving_shed_precision`` (fraction of adaptively shed requests
    that DID miss their deadline in the shed-off control pass — same
    schedule, traced keep-all), and ``serving_shed_overhead_ratio``
    (the controller's clean-path open-loop p50 cost via the shared
    ABBA protocol; must stay < 1.05x).

    ``BENCH_SERVING_QUANT=1`` runs the QUANTIZED-SERVING A/B instead
    (docs/SERVING.md "Quantized serving"): fp32 vs int8/bf16
    weight-only serving of the same weights under one open-loop
    schedule — sustained QPS, p99, device-resident param bytes and
    the fixture accuracy delta (``_bench_serving_quant``).

    ``BENCH_SERVING_SWAP=1`` runs the HOT-SWAP bench instead
    (docs/SERVING.md "Hot model swap"): one open-loop schedule with a
    mid-run ``server.swap()`` to a second model version, emitting
    ``serving_swap_p99_ratio`` (p99 of requests whose lifetime
    overlaps the swap window vs steady-state) and
    ``serving_swap_blip_ms`` (the longest completion silence
    overlapping the cutover — the stall an operator would see).

    ``BENCH_SERVING_HTTP=1`` runs the FRONT-DOOR bench instead
    (docs/SERVING.md "Front door"): the same open-loop schedule
    through the wire (``HttpFrontDoor`` + persistent ``WireClient``
    connections) vs in-process ``submit``, ABBA-interleaved, emitting
    ``serving_http_vs_inproc_p99_ratio`` (``_bench_serving_http``)."""
    import queue as _queue
    import tempfile
    import threading

    import jax

    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.monitor.registry import REGISTRY
    from paddle_tpu.serving import InferenceServer, ServingConfig

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    n_reqs = int(os.environ.get("BENCH_SERVING_REQS",
                                "600" if on_tpu else "200"))
    replicas = int(os.environ.get("BENCH_SERVING_REPLICAS", "1"))
    max_batch = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "8"))
    rate_x = float(os.environ.get("BENCH_SERVING_RATE_X", "3.0"))
    max_wait_ms = float(os.environ.get("BENCH_SERVING_MAX_WAIT_MS",
                                       "2.0"))

    # branch BEFORE freezing the shared dir / warm-booting the
    # baseline predictor: the quant A/B freezes its own same-weights
    # pair, and neither chaos nor swap uses the predictor
    if os.environ.get("BENCH_SERVING_QUANT") == "1":
        return _bench_serving_quant(max_batch, max_wait_ms)

    d = _freeze_serving_mlp(tempfile.mkdtemp())
    rng = np.random.RandomState(0)
    feed = rng.rand(1, 256).astype(np.float32)

    if os.environ.get("BENCH_SERVING_CHAOS") == "1":
        return _bench_serving_chaos(d, feed, max_batch, max_wait_ms)
    if os.environ.get("BENCH_SERVING_SWAP") == "1":
        return _bench_serving_swap(d, feed, max_batch, max_wait_ms)
    if os.environ.get("BENCH_SERVING_HTTP") == "1":
        return _bench_serving_http(d, feed, max_batch, max_wait_ms,
                                   replicas)

    base = create_predictor(Config(d))
    np.asarray(base.run({"x": feed})[0])       # compile once, shared

    # single-request service time -> offered rate for BOTH systems
    probes = 30 if not on_tpu else 50
    t0 = time.perf_counter()
    for _ in range(probes):
        base.run({"x": feed})
    svc_s = (time.perf_counter() - t0) / probes
    offered = rate_x * replicas / svc_s
    # ONE deterministic Poisson schedule shared by both systems —
    # "equal offered load" is literal, not statistical
    sched = np.cumsum(np.random.RandomState(42).exponential(
        1.0 / offered, size=n_reqs))

    def open_loop(submit):
        """Fire submit(i, t_arrival_abs) at each scheduled instant;
        returns the schedule origin."""
        t_origin = time.perf_counter()
        for i in range(n_reqs):
            delay = t_origin + sched[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            submit(i, t_origin + sched[i])
        return t_origin

    def line_from(tag, t_origin, done_at, lat_s, extra=None):
        lat_ms = np.sort(np.asarray(lat_s)) * 1e3
        sustained = n_reqs / (max(done_at) - t_origin)
        row = {
            "metric": f"serving_{tag}_qps",
            "value": round(sustained, 1), "unit": "req/s",
            "offered_qps": round(offered, 1),
            "n_requests": n_reqs,
            "replicas": replicas,
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        }
        row.update(extra or {})
        print(json.dumps(row))
        return sustained

    # ---- baseline: single-request Predictor dispatch -----------------
    work = _queue.Queue()
    done_at = [0.0] * n_reqs
    lat = [0.0] * n_reqs
    errs = []

    def worker(c):
        try:
            np.asarray(c.run({"x": feed})[0])  # warm this clone
            while True:
                item = work.get()
                if item is None:
                    return
                i, t_arr = item
                np.asarray(c.run({"x": feed})[0])
                done_at[i] = time.perf_counter()
                lat[i] = done_at[i] - t_arr
        except Exception as e:                  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(base.clone(),),
                                daemon=True) for _ in range(replicas)]
    for t in threads:
        t.start()
    t_origin = open_loop(lambda i, ta: work.put((i, ta)))
    for _ in threads:
        work.put(None)
    for t in threads:
        t.join(600)
    if errs or any(t.is_alive() for t in threads):
        print(json.dumps({
            "metric": "serving_baseline_error",
            "value": str(errs[0]) if errs else "worker stalled"}))
        return
    base_qps = line_from("baseline", t_origin, done_at, lat,
                         extra={"service_ms":
                                round(svc_s * 1e3, 3)})

    # ---- server: continuous micro-batching ---------------------------
    fill_m = REGISTRY.get("serving_batch_fill_ratio")
    fill0 = (fill_m.sum(), fill_m.count()) if fill_m else (0.0, 0)
    srv = InferenceServer(d, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        # the open loop never sheds: a full queue would drop requests
        # and flatter the tail, so admission is sized to the run
        max_queue=n_reqs + replicas, replicas=replicas))
    pend = [None] * n_reqs
    arrived = [0.0] * n_reqs
    t_origin = open_loop(lambda i, ta: (
        arrived.__setitem__(i, ta),
        pend.__setitem__(i, srv.submit({"x": feed}))))
    for p in pend:
        p.result(timeout=600)
    srv.close()
    done_at = [p.t_done for p in pend]
    lat = [p.t_done - ta for p, ta in zip(pend, arrived)]
    fill_m = REGISTRY.get("serving_batch_fill_ratio")
    dsum = fill_m.sum() - fill0[0]
    dcount = fill_m.count() - fill0[1]
    srv_qps = line_from(
        "server", t_origin, done_at, lat,
        extra={"max_batch": max_batch, "max_wait_ms": max_wait_ms,
               "batch_fill_ratio":
               round(dsum / dcount, 4) if dcount else None,
               "micro_batches": dcount})
    print(json.dumps({
        "metric": "serving_server_vs_baseline_qps",
        "value": round(srv_qps / base_qps, 3), "unit": "x",
        "vs_baseline": round(srv_qps / base_qps, 3),
    }))
    print(f"# open-loop serving: offered {offered:.0f} req/s "
          f"(rate_x={rate_x} x measured {1 / svc_s:.0f}/s x "
          f"{replicas} replica(s)), baseline {base_qps:.0f} vs "
          f"server {srv_qps:.0f} sustained", file=sys.stderr)

    # ---- tracing: p99 attribution + on/off overhead ------------------
    # Attribution pass: the SAME open-loop schedule, traced keep-all
    # (monitor/trace.py) — every request's span tree lands in the
    # ring, so the slowest decile's time splits into queue-wait /
    # execute / deliver shares BY MEASUREMENT, not guesswork. The
    # headline A/B above stays untraced; tracing's own cost is the
    # separate interleaved ratio below.
    from paddle_tpu.monitor import trace as mtrace

    mtrace.enable(sample_rate=1.0, capacity=max(8 * n_reqs, 4096))
    srv = InferenceServer(d, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=n_reqs + replicas, replicas=replicas))
    pend = [None] * n_reqs
    arrived = [0.0] * n_reqs
    t_origin = open_loop(lambda i, ta: (
        arrived.__setitem__(i, ta),
        pend.__setitem__(i, srv.submit({"x": feed}))))
    for p in pend:
        p.result(timeout=600)
    srv.close()
    lat = np.asarray([p.t_done - ta for p, ta in zip(pend, arrived)])
    n_dec = max(1, n_reqs // 10)
    phases = ("queue_wait", "batch_form", "dispatch_wait", "execute",
              "deliver")
    shares = {k: [] for k in phases}
    for i in np.argsort(lat)[::-1][:n_dec]:
        durs = {}
        for s in mtrace.spans(pend[int(i)].trace_id):
            durs[s["name"].split("/", 1)[1]] = \
                durs.get(s["name"].split("/", 1)[1], 0.0) + s["dur"]
        total = durs.get("request", 0.0)
        if total <= 0:
            continue
        for k in phases:
            shares[k].append(durs.get(k, 0.0) / total)
    print(json.dumps({
        "metric": "serving_p99_attribution",
        "value": round(float(np.percentile(lat * 1e3, 99)), 2),
        "unit": "ms", "n_slowest": n_dec,
        **{f"{k}_share":
           (round(float(np.median(v)), 4) if v else None)
           for k, v in shares.items()},
    }))
    mtrace.disable()

    # Overhead pass: tracing-on/off A/B of the p50 request latency
    # under sub-saturation OPEN-LOOP load — the regime serving SLOs
    # are about (the hot-path tracing cost is µs against ms-scale
    # latencies; a throughput-mode µbench of this host's GIL
    # scheduling cannot resolve it honestly). The shared
    # _abba_overhead protocol (ABBA quadruples + trimmed-mean +
    # sequential more-pairs) cancels the host's load drift; the smoke
    # test asserts the estimate < 1.05x.
    pairs = int(os.environ.get("BENCH_SERVING_TRACE_PAIRS", "3"))
    win = int(os.environ.get("BENCH_SERVING_TRACE_WIN", "120"))
    mtrace.enable(sample_rate=0.05, slow_keep=8)    # default policy,
    mtrace.disable()                                # tracer persists
    srv = InferenceServer(d, ServingConfig(
        max_batch=max_batch, max_wait_ms=max_wait_ms,
        max_queue=4 * win, replicas=replicas))
    t0 = time.perf_counter()
    for _ in range(20):
        srv.infer({"x": feed}, timeout=60)
    ab_rate = 0.5 * replicas / ((time.perf_counter() - t0) / 20)
    ab_rng = np.random.RandomState(7)

    def p50_window(traced, n=win):
        if traced:
            mtrace.enable()
        else:
            mtrace.disable()
        sched = np.cumsum(ab_rng.exponential(1.0 / ab_rate, size=n))
        t0 = time.perf_counter()
        pend = []
        for i in range(n):
            dly = t0 + sched[i] - time.perf_counter()
            if dly > 0:
                time.sleep(dly)
            pend.append((srv.submit({"x": feed}), t0 + sched[i]))
        lat_w = []
        for p, ta in pend:
            p.result(timeout=120)
            lat_w.append(p.t_done - ta)
        return float(np.median(lat_w)) * 1e3

    p50_window(True), p50_window(False)             # warm both paths
    est, pair_ratios, on_ms, off_ms = _abba_overhead(p50_window, pairs)
    mtrace.disable()
    print(json.dumps({
        "metric": "serving_trace_overhead_ratio",
        "value": round(est, 4), "unit": "x",
        "traced_p50_ms": round(float(np.median(on_ms)), 4),
        "untraced_p50_ms": round(float(np.median(off_ms)), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios],
        "window_reqs": win, "offered_fraction_of_capacity": 0.5,
    }))

    # Memory-poller overhead pass (monitor/memory.py): identical
    # open-loop protocol and server, with the live-buffer poller
    # sampling at a deliberately hostile 50 ms interval vs fully off
    # (disable == zero recording — no thread, no gauge writes). The
    # poller aggregates jax.live_arrays on its own daemon thread, so
    # this measures the GIL/allocator shadow it casts over request
    # latency; the smoke test asserts the ABBA estimate < 1.05x.
    from paddle_tpu.monitor import memory as _memory
    mem_pairs = int(os.environ.get("BENCH_SERVING_MEM_PAIRS",
                                   str(pairs)))

    def p50_mem_window(polling, n=win):
        if polling:
            _memory.enable(interval=0.05)
        else:
            _memory.disable()
        sched = np.cumsum(ab_rng.exponential(1.0 / ab_rate, size=n))
        t0 = time.perf_counter()
        pend = []
        for i in range(n):
            dly = t0 + sched[i] - time.perf_counter()
            if dly > 0:
                time.sleep(dly)
            pend.append((srv.submit({"x": feed}), t0 + sched[i]))
        lat_w = []
        for p, ta in pend:
            p.result(timeout=120)
            lat_w.append(p.t_done - ta)
        return float(np.median(lat_w)) * 1e3

    p50_mem_window(True), p50_mem_window(False)     # warm both paths
    est_m, pair_ratios_m, on_m, off_m = _abba_overhead(p50_mem_window,
                                                       mem_pairs)
    _memory.disable()
    print(json.dumps({
        "metric": "memory_overhead_ratio", "path": "serving",
        "value": round(est_m, 4), "unit": "x",
        "polled_p50_ms": round(float(np.median(on_m)), 4),
        "unpolled_p50_ms": round(float(np.median(off_m)), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios_m],
        "poll_interval_s": 0.05, "window_reqs": win,
        "offered_fraction_of_capacity": 0.5,
    }))

    # Goodput-ledger overhead pass (monitor/goodput.py): identical
    # open-loop protocol and server, ledger armed vs disarmed. Serving
    # is deliberately NOT instrumented by the ledger (it attributes
    # the training loop), so armed-vs-off here proves the ledger's
    # module-global arm check casts no shadow over an unrelated hot
    # path; the smoke test asserts the ABBA estimate < 1.05x.
    from paddle_tpu.monitor import goodput as _goodput
    gp_pairs = int(os.environ.get("BENCH_SERVING_GOODPUT_PAIRS",
                                  str(pairs)))

    def p50_gp_window(armed, n=win):
        if armed:
            _goodput.enable()
        else:
            _goodput.disable()
        sched = np.cumsum(ab_rng.exponential(1.0 / ab_rate, size=n))
        t0 = time.perf_counter()
        pend = []
        for i in range(n):
            dly = t0 + sched[i] - time.perf_counter()
            if dly > 0:
                time.sleep(dly)
            pend.append((srv.submit({"x": feed}), t0 + sched[i]))
        lat_w = []
        for p, ta in pend:
            p.result(timeout=120)
            lat_w.append(p.t_done - ta)
        return float(np.median(lat_w)) * 1e3

    p50_gp_window(True), p50_gp_window(False)       # warm both paths
    est_g, pair_ratios_g, on_g, off_g = _abba_overhead(p50_gp_window,
                                                       gp_pairs)
    _goodput.disable()
    srv.close()
    print(json.dumps({
        "metric": "goodput_overhead_ratio", "path": "serving",
        "value": round(est_g, 4), "unit": "x",
        "armed_p50_ms": round(float(np.median(on_g)), 4),
        "disarmed_p50_ms": round(float(np.median(off_g)), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios_g],
        "window_reqs": win, "offered_fraction_of_capacity": 0.5,
    }))


def _bench_serving_chaos(d, feed, max_batch, max_wait_ms):
    """The resilience half of `bench.py serving`
    (BENCH_SERVING_CHAOS=1). Three measurements on the 2-replica
    server, each a paired A/B on the same deterministic schedule:

    - ``serving_chaos_p99_ratio``: open-loop load at ~0.5x capacity,
      clean vs one replica wedged mid-load (PT_FAULT_REPLICA_STALL) —
      the ratio of the UNAFFECTED requests' p99; the wedged batch's
      riders resolve as typed errors and are reported, never hidden
      in the percentile.
    - ``serving_shed_precision``: overload (~2.5x capacity) with
      deadlines, shed OFF (traced keep-all — ground truth for who
      missed) vs shed adaptive — precision = shed requests that would
      in fact have missed their deadline.
    - ``serving_shed_overhead_ratio``: the controller's clean-path
      cost, ABBA-interleaved open-loop p50 at ~0.5x capacity with the
      controller swapped in/out (the shared _abba_overhead protocol);
      the smoke test pins < 1.05x.

    Knobs: BENCH_SERVING_CHAOS_REQS / _STALL_MS / _DEADLINE_MS /
    _SHED_PAIRS / _SHED_WIN."""
    from paddle_tpu.monitor import trace as mtrace
    from paddle_tpu.monitor.registry import REGISTRY
    from paddle_tpu.serving import (DeadlineExceededError,
                                    InferenceServer, OverloadedError,
                                    QueueFullError, ReplicaLostError,
                                    ServingConfig, ShedController)
    from paddle_tpu.testing import faults

    n = int(os.environ.get("BENCH_SERVING_CHAOS_REQS", "200"))
    stall_ms = float(os.environ.get("BENCH_SERVING_STALL_MS", "300"))
    replicas = 2

    def boot(**kw):
        kw.setdefault("max_batch", max_batch)
        kw.setdefault("max_wait_ms", max_wait_ms)
        kw.setdefault("max_queue", 4 * n + 64)
        kw.setdefault("replicas", replicas)
        kw.setdefault("replica_stall_ms", stall_ms)
        kw.setdefault("respawn_backoff_ms", 20.0)
        return InferenceServer(d, ServingConfig(**kw))

    def open_loop(srv, sched_arr, deadline_ms=None, timeout=120):
        """Submit on the schedule; returns per-request (ok_latency_s
        | exception-class-name | 'hang')."""
        pend = [None] * len(sched_arr)
        t0 = time.perf_counter()
        for i, t_arr in enumerate(sched_arr):
            dly = t0 + t_arr - time.perf_counter()
            if dly > 0:
                time.sleep(dly)
            try:
                pend[i] = (srv.submit({"x": feed},
                                      deadline_ms=deadline_ms),
                           t0 + t_arr)
            except (OverloadedError, DeadlineExceededError,
                    QueueFullError) as e:
                pend[i] = (e, None)
        out = []
        for p, t_arr in pend:
            if not hasattr(p, "result"):
                out.append(type(p).__name__)
                continue
            try:
                p.result(timeout=timeout)
                out.append(p.t_done - t_arr)
            except TimeoutError:
                out.append("hang")
            except Exception as e:
                out.append(type(e).__name__)
        return out

    def warm(srv, rounds=3):
        # sequential singles warm the 1-bucket; concurrent bursts
        # coalesce into the larger buckets so EVERY executable has
        # run before a timed pass (first executions pay one-time
        # transfer/donation setup that would otherwise land in
        # whichever pass ran first)
        for _ in range(6):
            srv.infer({"x": feed}, timeout=60)
        for _ in range(rounds):
            for p in [srv.submit({"x": feed}) for _ in range(16)]:
                p.result(timeout=60)

    # -- capacity probe on a clean warm server -------------------------
    srv = boot()
    warm(srv)
    t0 = time.perf_counter()
    for _ in range(30):
        srv.infer({"x": feed}, timeout=60)
    svc_s = (time.perf_counter() - t0) / 30
    half_rate = 0.5 * replicas / svc_s

    # -- chaos A/B: clean pass, then one replica wedged mid-load -------
    sched = np.cumsum(np.random.RandomState(42).exponential(
        1.0 / half_rate, size=n))
    clean = open_loop(srv, sched)
    srv.close(timeout=120)
    clean_ok = [x for x in clean if isinstance(x, float)]
    p99_clean = float(np.percentile(np.asarray(clean_ok) * 1e3, 99))

    resp_m = REGISTRY.get("serving_replica_respawns_total")
    resp0 = resp_m.value() if resp_m else 0.0
    srv = boot()
    warm(srv)       # same warm-up as the clean pass, pre-arm
    os.environ["PT_FAULT_REPLICA_STALL"] = "8"
    os.environ["PT_FAULT_REPLICA"] = "1"
    os.environ["PT_FAULT_STALL_SECS"] = "120"
    faults._serving_fired.discard("replica_stall")
    uninstall = faults.install_serving_faults()
    try:
        chaos = open_loop(srv, sched)
    finally:
        uninstall()
        for k in ("PT_FAULT_REPLICA_STALL", "PT_FAULT_REPLICA",
                  "PT_FAULT_STALL_SECS"):
            os.environ.pop(k, None)
    # the respawn lands after quarantine + backoff — give the
    # supervisor a bounded moment (BEFORE close stops it) so the row
    # reports the heal
    lost_any = any(x == "ReplicaLostError" for x in chaos)
    heal_by = time.monotonic() + (10 if lost_any else 0)
    while time.monotonic() < heal_by:
        if resp_m is not None and resp_m.value() > resp0:
            break
        time.sleep(0.02)
    srv.close(timeout=120)
    chaos_ok = [x for x in chaos if isinstance(x, float)]
    hangs = sum(1 for x in chaos if x == "hang")
    lost = sum(1 for x in chaos if x == "ReplicaLostError")
    p99_chaos = float(np.percentile(np.asarray(chaos_ok) * 1e3, 99))
    print(json.dumps({
        "metric": "serving_chaos_p99_ratio",
        "value": round(p99_chaos / p99_clean, 3), "unit": "x",
        "clean_p99_ms": round(p99_clean, 2),
        "chaos_p99_ok_ms": round(p99_chaos, 2),
        "n_requests": n, "replicas": replicas,
        "stall_ms": stall_ms,
        "lost_requests": lost, "hangs": hangs,
        "respawns": round((resp_m.value() if resp_m else 0.0)
                          - resp0, 0),
    }))

    # -- shed precision: overload with deadlines, off vs adaptive ------
    # the shed passes serve single-request buckets (max_batch=1):
    # continuous batching multiplies capacity severalfold, so a
    # deterministic sustained overload of a batching ladder would
    # need tens of thousands of requests to hold queue pressure for
    # long enough to observe the controller — with batch=1 the same
    # 2.5x overload holds for the whole pass and the admission
    # mechanism (what this row measures) is identical
    deadline_ms = float(os.environ.get("BENCH_SERVING_DEADLINE_MS")
                        or max(6 * svc_s * 1e3, 20.0))
    n_ov = max(4 * n, 800)
    # true single-bucket capacity, closed loop: the open-loop probe's
    # svc_s includes max_wait_ms batching slack, and an "overload"
    # derived from it can sit at the capacity knife-edge where queue
    # wait never grows and nothing sheds
    srv = boot(max_batch=1, max_queue=n_ov + 64)
    burst = [srv.submit({"x": feed}) for _ in range(200)]
    tb = time.perf_counter()
    for p in burst:
        p.result(timeout=120)
    rate1 = 200 / (time.perf_counter() - tb)
    srv.close(timeout=120)
    over_rate = 2.5 * rate1
    sched_ov = np.cumsum(np.random.RandomState(7).exponential(
        1.0 / over_rate, size=n_ov))
    # ground truth: shed OFF on the same schedule — who actually
    # missed. BOTH passes run keep-all traced (the evidence trail for
    # per-request postmortems) so tracing's cost cancels out of the
    # A/B instead of loading only the control side; try/finally so an
    # exception can't leave process-global tracing enabled
    mtrace.enable(sample_rate=1.0, capacity=max(8 * n_ov, 4096))
    try:
        srv = boot(default_deadline_ms=deadline_ms, max_batch=1,
                   max_queue=n_ov + 64)
        control = open_loop(srv, sched_ov)
        srv.close(timeout=120)
        missed = {i for i, x in enumerate(control)
                  if x == "DeadlineExceededError"}
        # adaptive pass on the SAME schedule
        srv = boot(default_deadline_ms=deadline_ms,
                   shed_mode="adaptive", max_batch=1,
                   max_queue=n_ov + 64)
        adaptive = open_loop(srv, sched_ov)
        srv.close(timeout=120)
    finally:
        mtrace.disable()
    shed = {i for i, x in enumerate(adaptive)
            if x == "OverloadedError"}
    precision = (round(len(shed & missed) / len(shed), 4)
                 if shed else None)
    print(json.dumps({
        "metric": "serving_shed_precision",
        "value": precision, "unit": "fraction",
        "n_shed": len(shed), "n_missed_control": len(missed),
        "deadline_ms": round(deadline_ms, 2),
        "overload_x": 2.5, "n_requests": n_ov, "max_batch": 1,
    }))

    # -- shed controller overhead on the clean path (ABBA p50) ---------
    pairs = int(os.environ.get("BENCH_SERVING_SHED_PAIRS", "3"))
    win = int(os.environ.get("BENCH_SERVING_SHED_WIN", "100"))
    srv = boot(default_deadline_ms=10_000.0)
    ctrl = ShedController(deadline_ms=10_000.0)
    ab_rng = np.random.RandomState(11)

    def p50_window(shed_on, n_w=win):
        # swapping the controller in/out of the live scheduler is the
        # honest A/B: admission checks `self._shed is not None`
        srv.scheduler._shed = ctrl if shed_on else None
        sched_w = np.cumsum(ab_rng.exponential(1.0 / half_rate,
                                               size=n_w))
        lat = open_loop(srv, sched_w, timeout=120)
        return float(np.median([x for x in lat
                                if isinstance(x, float)])) * 1e3

    p50_window(True), p50_window(False)         # warm both paths
    est, pair_ratios, on_ms, off_ms = _abba_overhead(p50_window, pairs)
    srv.scheduler._shed = None
    srv.close(timeout=120)
    print(json.dumps({
        "metric": "serving_shed_overhead_ratio",
        "value": round(est, 4), "unit": "x",
        "shed_on_p50_ms": round(float(np.median(on_ms)), 4),
        "shed_off_p50_ms": round(float(np.median(off_ms)), 4),
        "pair_ratios": [round(r, 4) for r in pair_ratios],
        "window_reqs": win, "offered_fraction_of_capacity": 0.5,
    }))


def bench_longcontext():
    """`python bench.py longcontext` — BERT-base training throughput at
    long sequence lengths on the Pallas flash-attention kernels (the
    numbers BASELINE.md's long-context claims cite). One JSON line per
    length; vs_baseline = speedup over XLA dense attention at the same
    length (both measured here)."""
    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh, set_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    mesh = set_mesh(make_mesh(MeshConfig(data=1),
                              devices=jax.devices()[:1]))
    configs = ([(2048, 8), (4096, 4)] if on_tpu else [(128, 2)])
    steps = 10 if on_tpu else 2

    def run(seq, batch, impl):
        # each impl at its best memory-feasible config: flash fits
        # without remat (O(block.S) attention memory); dense needs remat
        # at these lengths (the O(S^2) scores blow HBM otherwise)
        remat = impl == "dense"
        cfg = (bert.bert_base(max_seq=seq, attention_impl=impl,
                              remat=remat) if on_tpu
               else bert.bert_tiny(max_seq=seq, attention_impl=impl))
        opt = pt.optimizer.Adam(learning_rate=1e-4)
        # spc=4 stays the long-context default: the r3 A/B measured
        # 2048-flash 89.3k at spc=8 vs 91.2k at spc=4 (4096: 65.6k vs
        # 64.9k — a wash), so the bigger scan hurts at the larger
        # activation footprint. BENCH_SPC overrides.
        spc = int(os.environ.get("BENCH_SPC", "4" if on_tpu else "1"))
        init_fn, step_fn = bert.make_train_step(cfg, opt, mesh,
                                                steps_per_call=spc)
        data = bert.synthetic_batch(cfg, batch_size=batch, seq_len=seq)
        params, opt_state = init_fn(jax.random.PRNGKey(0))

        def once(carry):
            params, opt_state = carry
            loss, params, opt_state = step_fn(params, opt_state, data)
            return (params, opt_state), loss

        tr = _timed_steps(once, (params, opt_state), steps, settle=2,
                          sub_steps=spc)
        return batch * seq * steps * spc / tr.dt, tr

    for seq, batch in configs:
        tps_flash, tr_flash = run(seq, batch, "flash")
        tps_dense, tr_dense = run(seq, batch, "dense")
        line = {
            "metric": f"bert_base_seq{seq}_flash_tokens_per_sec",
            "value": round(tps_flash, 2), "unit": "tokens/sec",
            "vs_baseline": round(tps_flash / tps_dense, 4),
            **tr_flash.extras()}
        if tr_dense.contention_suspected:
            # the denominator of vs_baseline was contended: the speedup
            # claim is suspect even if the flash windows were quiet
            line["contention_suspected"] = True
            line["dense_baseline_contended"] = True
        print(json.dumps(line))


def bench_nmt():
    """`python bench.py nmt`: Transformer-big WMT shape (bs=32, s=256)
    train tokens/sec + MFU, plus beam-search decode latency (the
    reference's stress test, operators/beam_search_op.cc)."""
    import functools

    import jax

    import paddle_tpu as pt
    from paddle_tpu.models import transformer as T
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh, set_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    mesh = set_mesh(make_mesh(MeshConfig(data=1),
                              devices=jax.devices()[:1]))
    bs, s = (32, 256) if on_tpu else (2, 16)
    cfg = (T.transformer_big(max_seq=s) if on_tpu
           else T.transformer_tiny(max_seq=s))
    opt = pt.optimizer.Adam(1e-4)
    init_fn, step_fn = T.make_train_step(cfg, opt, mesh)
    batch = T.synthetic_batch(cfg, bs, src_len=s, tgt_len=s)
    params, opt_state = init_fn(jax.random.PRNGKey(0))
    steps = 20 if on_tpu else 2

    def once(carry):
        params, opt_state = carry
        loss, params, opt_state = step_fn(params, opt_state, batch)
        return (params, opt_state), loss

    tr = _timed_steps(once, (params, opt_state), steps)
    params, _ = tr.carry
    tok_s = bs * s * steps / tr.dt
    mfu = (T.flops_per_step(cfg, bs, s, s) * steps / tr.dt) / 197e12
    print(json.dumps({
        "metric": "transformer_big_train_target_tokens_per_sec_per_chip",
        "value": round(tok_s, 1), "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.35, 4),
        **tr.extras()}))

    # beam-search decode latency
    max_len = 64 if on_tpu else 8
    bsd = jax.jit(functools.partial(T.beam_search_decode, cfg=cfg,
                                    beam_size=4, max_len=max_len))

    def decode_once(carry):
        out = bsd(params, src_ids=batch["src_ids"],
                  src_mask=batch["src_mask"])
        return carry, jax.tree.leaves(out)[0]

    reps = 5 if on_tpu else 1
    tr = _timed_steps(decode_once, None, reps, settle=1)
    line = {
        "metric": "transformer_big_beam4_decode_latency_ms",
        "value": round(tr.dt / reps * 1e3, 1), "unit": "ms",
        "decode_tokens_per_sec": round(bs * max_len * reps / tr.dt, 1)}
    if tr.contention_suspected:
        line["contention_suspected"] = True
    print(json.dumps(line))


def bench_numerics():
    """`python bench.py numerics` — step-time overhead of the
    FLAGS_check_nan_inf in-graph sentinels (monitor/numerics.py),
    measured the bench_dispatch way: check-on and check-off windows
    INTERLEAVE (adjacent windows see the same ambient host load on a
    shared box), and the headline is the median of per-pair on/off
    ratios, which a load drift cannot bias. The model is the
    deep-and-narrow dispatch-bound stack — the worst case for the
    sentinel, whose reduction cost is trivial but whose per-step
    scalar sync and no-donation policy hit exactly the host-bound
    regime. Prints one JSON line; windows also land in the registry
    snapshot every bench mode emits."""
    import time as _time

    import paddle_tpu as pt
    from paddle_tpu.static.executor import Scope, scope_guard

    steps = int(os.environ.get("BENCH_NUMERICS_STEPS", "150"))
    # mode-specific knob: BENCH_WINDOWS means "timed windows" in every
    # other mode, and silently reading it as PAIRS here would double
    # this mode's runtime under the shared CI knob
    pairs = max(2, int(os.environ.get("BENCH_NUMERICS_PAIRS", "5")))
    DEPTH, HIDDEN, BATCH = 24, 16, 16

    pt.enable_static()
    rs = np.random.RandomState(0)
    xb = rs.randn(BATCH, HIDDEN).astype(np.float32)
    yb = rs.randn(BATCH, 1).astype(np.float32)
    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup):
        x = pt.static.data("x", shape=[HIDDEN])
        y = pt.static.data("y", shape=[1])
        h = x
        for i in range(DEPTH):
            h = pt.layers.fc(h, size=HIDDEN, param_attr=f"w{i}",
                             bias_attr=f"b{i}", act="relu")
        pred = pt.layers.fc(h, size=1, param_attr="w_out",
                            bias_attr="b_out")
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Momentum(0.02, momentum=0.9).minimize(loss)
    scope = Scope()

    def window(check, n):
        pt.set_flags({"check_nan_inf": check})
        try:
            with scope_guard(scope):
                t0 = _time.perf_counter()
                for _ in range(n):
                    exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss])
                return _time.perf_counter() - t0
        finally:
            pt.set_flags({"check_nan_inf": False})

    with scope_guard(scope):
        exe = pt.static.Executor()
        exe.run(startup)
    window(False, 4)            # compile + warm both variants: the
    window(True, 4)             # checked jit is its own trace/compile
    on_ms, off_ms, ratios = [], [], []
    from paddle_tpu.monitor.registry import histogram
    h_win = histogram("bench_window_ms_per_step",
                      "Per-step wall ms of each timed bench window")
    for w in range(pairs):
        first_on = w % 2 == 0   # alternate order within each pair
        a = window(first_on, steps)
        b = window(not first_on, steps)
        on, off = (a, b) if first_on else (b, a)
        on_ms.append(on / steps * 1e3)
        off_ms.append(off / steps * 1e3)
        ratios.append(on / off)
        h_win.observe(on / steps * 1e3)
        h_win.observe(off / steps * 1e3)
    med = float(np.median(ratios))
    print(json.dumps({
        "metric": "numerics_check_overhead_ratio",
        "value": round(med, 4), "unit": "x",
        "check_on_ms_per_step": round(float(np.median(on_ms)), 4),
        "check_off_ms_per_step": round(float(np.median(off_ms)), 4),
        "pair_ratios": [round(r, 4) for r in ratios],
    }))
    print(f"# numerics sentinel overhead: median pair ratio "
          f"{med:.4f}x over {pairs} interleaved pairs x {steps} steps",
          file=sys.stderr)


def bench_ckpt():
    """`python bench.py ckpt` — checkpoint durability-path timings:
    save (serialize + CRC + fsync + atomic publish) and restore with
    digest verification ON vs OFF, so the integrity overhead is
    measured, not assumed. Verify-on and verify-off restore windows
    INTERLEAVE (the bench_dispatch discipline: adjacent windows see
    the same ambient disk/host load on a shared box) and the headline
    is the median of per-pair on/off ratios. BENCH_CKPT_MB sets the
    payload size, BENCH_CKPT_PAIRS the pair count. Three JSON lines:
    ckpt_save_ms, ckpt_restore_ms, ckpt_verify_overhead_ratio."""
    import shutil
    import tempfile
    import time as _time

    from paddle_tpu.io_checkpoint import CheckpointManager

    mb = float(os.environ.get("BENCH_CKPT_MB", "64"))
    pairs = max(2, int(os.environ.get("BENCH_CKPT_PAIRS", "5")))
    n_arrays = 16
    per = max(int(mb * 1e6 / 4 / n_arrays), 1)
    rs = np.random.RandomState(0)
    tree = {"params": {f"w{i}": rs.randn(per).astype(np.float32)
                       for i in range(n_arrays)},
            "opt": {f"m{i}": rs.randn(per).astype(np.float32)
                    for i in range(2)}}
    nbytes = sum(a.nbytes for g in tree.values() for a in g.values())
    d = tempfile.mkdtemp(prefix="bench_ckpt_")
    try:
        mgr = CheckpointManager(d, async_save=False,
                                save_interval_steps=1, keep_max=2)
        mgr.save(0, tree)               # warmup (dir entries, caches)
        save_ms = []
        for i in range(1, pairs + 1):
            t0 = _time.perf_counter()
            mgr.save(i, tree)
            save_ms.append((_time.perf_counter() - t0) * 1e3)
        step = mgr.latest_step()
        mgr.restore(step)               # warmup both restore paths
        mgr.restore(step, verify=False)
        on_ms, off_ms, ratios = [], [], []
        for w in range(pairs):
            first_on = w % 2 == 0       # alternate order within pairs

            def timed(verify):
                t0 = _time.perf_counter()
                mgr.restore(step, verify=verify)
                return (_time.perf_counter() - t0) * 1e3

            a = timed(first_on)
            b = timed(not first_on)
            on, off = (a, b) if first_on else (b, a)
            on_ms.append(on)
            off_ms.append(off)
            ratios.append(on / off)
        mgr.close()
        med = float(np.median(ratios))
        save_med = float(np.median(save_ms))
        print(json.dumps({
            "metric": "ckpt_save_ms", "value": round(save_med, 2),
            "unit": "ms", "payload_mb": round(nbytes / 1e6, 1),
            "save_mb_per_sec": round(nbytes / 1e6 / (save_med / 1e3), 1),
        }))
        print(json.dumps({
            "metric": "ckpt_restore_ms",
            "value": round(float(np.median(on_ms)), 2), "unit": "ms",
            "verify_on_ms": round(float(np.median(on_ms)), 2),
            "verify_off_ms": round(float(np.median(off_ms)), 2),
        }))
        print(json.dumps({
            "metric": "ckpt_verify_overhead_ratio",
            "value": round(med, 4), "unit": "x",
            "pair_ratios": [round(r, 4) for r in ratios],
        }))
        print(f"# checkpoint verify overhead: median pair ratio "
              f"{med:.4f}x over {pairs} interleaved pairs, "
              f"{nbytes / 1e6:.0f} MB payload", file=sys.stderr)
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_data():
    """`python bench.py data` — data-plane A/B (ROADMAP item 4): the
    deterministic sharded NATIVE loader vs the Python oracle on the
    STATEFUL (exactly-once) path — the fast path PR 5/6 used to
    surrender — plus a stateless-native reference row and the
    device-side double-buffer on/off A/B.

    Protocol (the bench_dispatch discipline): each comparison runs as
    interleaved pairs — adjacent windows see the same ambient host
    load — and the headline is the median of per-pair ratios, which a
    load drift cannot bias. Every window consumes a fixed batch count
    from a FRESH loader over the same generated dataset (epochs=-1:
    no window ever hits end-of-stream early).

    JSON lines: data_{native_stateful,python_stateful,stateless}
    _records_per_sec, data_native_vs_python_ratio (>= 2x is ROADMAP
    item 4's bar; resume bit-identity is proven separately by the
    tests/test_data_plane.py conformance suite), and
    data_h2d_overlap_ratio (double-buffer OFF step time / ON step
    time; > 1.0 means the prefetch worker's device_put hid transfer
    under compute — expect ~1.0 on CPU, where jnp.asarray of a host
    batch is a no-copy alias; re-A/B on a real chip, where H2D is a
    PCIe/ICI hop: `JAX_PLATFORMS=tpu python bench.py data`).

    Env knobs: BENCH_DATA_FILES/ROWS/BATCH/BATCHES/PAIRS/SHUFFLE."""
    import shutil
    import tempfile
    import time as _time

    from paddle_tpu import native as _native
    from paddle_tpu.dataio.dataloader import FileDataLoader

    if not _native.available():
        raise RuntimeError(
            "bench.py data needs the native library (the A/B's whole "
            "point); the C++ toolchain is missing or the build failed")

    nfiles = int(os.environ.get("BENCH_DATA_FILES", "4"))
    rows = int(os.environ.get("BENCH_DATA_ROWS", "25000"))
    batch = int(os.environ.get("BENCH_DATA_BATCH", "256"))
    batches = int(os.environ.get("BENCH_DATA_BATCHES", "60"))
    pairs = max(2, int(os.environ.get("BENCH_DATA_PAIRS", "3")))
    shuffle = int(os.environ.get("BENCH_DATA_SHUFFLE", "1024"))

    d = tempfile.mkdtemp(prefix="bench_data_")
    try:
        files = []
        for i in range(nfiles):
            p = os.path.join(d, f"part-{i}.txt")
            with open(p, "w") as f:
                for j in range(rows):
                    f.write(f"{(i * rows + j) % 977}.5\n")
            files.append(p)

        def mk_loader(native, stateful=True, device_put=False):
            # minimal real parse (bytes -> number): the mode measures
            # the DATA PLANE; a heavyweight per-record parse_fn would
            # just flatten the A/B toward its own cost
            return FileDataLoader(
                files, float, batch_size=batch,
                nthreads=4, shuffle_buffer=shuffle, seed=7, epochs=-1,
                device_put=device_put, stateful=stateful,
                native=native)

        def window(native, stateful=True):
            """Wall seconds to consume `batches` fresh batches."""
            ld = mk_loader(native, stateful)
            it = iter(ld)
            next(it)                      # spin up worker + warm cache
            t0 = _time.perf_counter()
            for _ in range(batches):
                next(it)
            dt = _time.perf_counter() - t0
            it.close()
            return dt

        window(True)                      # warm the .so + page cache
        window(False)
        recs = batch * batches
        nat_rps, py_rps, ratios = [], [], []
        for w in range(pairs):
            first_nat = w % 2 == 0        # alternate order within pairs
            a = window(first_nat)
            b = window(not first_nat)
            nat, py = (a, b) if first_nat else (b, a)
            nat_rps.append(recs / nat)
            py_rps.append(recs / py)
            ratios.append(py / nat)       # >1: native faster
        stateless = [recs / window(True, stateful=False)
                     for _ in range(2)]
        med = float(np.median(ratios))
        print(json.dumps({
            "metric": "data_native_stateful_records_per_sec",
            "value": round(float(np.median(nat_rps))), "unit": "rec/s",
            "batch": batch, "shuffle_buffer": shuffle,
            "nfiles": nfiles}))
        print(json.dumps({
            "metric": "data_python_stateful_records_per_sec",
            "value": round(float(np.median(py_rps))), "unit": "rec/s"}))
        print(json.dumps({
            "metric": "data_stateless_records_per_sec",
            "value": round(float(np.median(stateless))),
            "unit": "rec/s"}))
        print(json.dumps({
            "metric": "data_native_vs_python_ratio",
            "value": round(med, 4), "unit": "x",
            "pair_ratios": [round(r, 4) for r in ratios]}))
        print(f"# stateful ingest: native {med:.2f}x the Python "
              f"oracle over {pairs} interleaved pairs x {batches} "
              f"batches of {batch}", file=sys.stderr)

        # ---- device-side double-buffer A/B --------------------------------
        import paddle_tpu as pt
        from paddle_tpu.static.executor import Scope, scope_guard

        steps = min(batches, 40)
        HIDDEN = 128
        pt.enable_static()
        try:
            main, startup = pt.Program(), pt.Program()
            with pt.program_guard(main, startup):
                x = pt.static.data("x", shape=[HIDDEN])
                h = x
                for i in range(4):
                    h = pt.layers.fc(h, size=HIDDEN,
                                     param_attr=f"w{i}",
                                     bias_attr=f"b{i}", act="relu")
                loss = pt.layers.mean(h)
            scope = Scope()
            with scope_guard(scope):
                exe = pt.static.Executor()
                exe.run(startup)

                rs = np.random.RandomState(0)
                feed_rows = rs.randn(batch, HIDDEN).astype(np.float32)

                def feed_loader(put):
                    # per-batch distinct rows (a copy per batch), so
                    # the put stage does real work every step
                    def gen():
                        for i in range(steps + 2):
                            yield feed_rows + np.float32(i)
                    from paddle_tpu.static.executor import \
                        background_prefetch
                    if put is None:
                        return background_prefetch(gen(), lambda b: b,
                                                   2)
                    return background_prefetch(gen(), put, 2)

                put = exe.feed_stage(main, feed_names=["x"])

                def step_window(double_buffer):
                    it = feed_loader(put if double_buffer else None)
                    b0 = next(it)                 # warm the pipeline
                    exe.run(main, feed={"x": b0}, fetch_list=[loss])
                    t0 = _time.perf_counter()
                    out = None
                    for b in it:
                        out = exe.run(main, feed={"x": b},
                                      fetch_list=[loss],
                                      return_numpy=False)
                    float(np.ravel(np.asarray(out[0]))[0])
                    dt = _time.perf_counter() - t0
                    it.close()
                    return dt

                step_window(True)                 # compile + warm both
                step_window(False)
                on_ms, off_ms, h2d_ratios = [], [], []
                for w in range(pairs):
                    first_on = w % 2 == 0
                    a = step_window(first_on)
                    b = step_window(not first_on)
                    on, off = (a, b) if first_on else (b, a)
                    on_ms.append(on / steps * 1e3)
                    off_ms.append(off / steps * 1e3)
                    h2d_ratios.append(off / on)   # >1: overlap won
                med_h = float(np.median(h2d_ratios))
                print(json.dumps({
                    "metric": "data_h2d_overlap_ratio",
                    "value": round(med_h, 4), "unit": "x",
                    "on_ms_per_step":
                        round(float(np.median(on_ms)), 4),
                    "off_ms_per_step":
                        round(float(np.median(off_ms)), 4),
                    "pair_ratios": [round(r, 4) for r in h2d_ratios],
                }))
                print(f"# double buffer: off/on step-time ratio "
                      f"{med_h:.4f}x ({'overlap pays' if med_h > 1.05 else 'within noise on this backend'})",
                      file=sys.stderr)
        finally:
            pt.disable_static()
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_shard():
    """`python bench.py shard` — unified-mesh topology sweep (ROADMAP
    item 2): one transformer trunk trained under the ShardingSpec
    partitioner across mesh topologies — pure-DP (`data=N`),
    model x data (megatron block sharding over "model"), and
    pipe x data (the fused 1F1B scan of parallel/pipeline.py) — on
    whatever devices are visible (the MULTICHIP harness provisions 8).

    Protocol: every topology compiles first, then timed windows
    INTERLEAVE round-robin across topologies (adjacent windows see the
    same ambient host load — the bench_dispatch discipline), and each
    topology reports its BEST window. One JSON line per topology:
    ms/step, MFU (analytic trunk FLOPs / step time / N x chip peak),
    and estimated collective bytes per step from the compiled HLO
    (monitor/cost.estimate_comm — SPMD inserts collectives at compile
    time, so the estimate reads the optimized executable text).

    The pipe topology also A/Bs FLAGS_overlap_grad_reduce (gradient
    all-reduce issued per-bucket inside the backward scan vs one
    epilogue reduction): overlap-on and overlap-off windows interleave
    in pairs and the headline is the median per-pair on/off ratio —
    < 1.0 means the in-scan reduction overlapped with compute.

    Env knobs: BENCH_SHARD_TOPOS (csv of dp,modelxdata,pipexdata),
    BENCH_SHARD_STEPS, BENCH_WINDOWS, BENCH_SHARD_PAIRS,
    BENCH_SHARD_HIDDEN/FFN/SEQ/BATCH/LAYERS/VOCAB/HEADS/MICRO."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.monitor import cost as _cost
    from paddle_tpu.monitor.registry import gauge
    from paddle_tpu.parallel import pipeline as pl
    from paddle_tpu.parallel.mesh import (
        DATA_AXIS, MODEL_AXIS, PIPE_AXIS, MeshConfig, make_mesh,
    )
    from paddle_tpu.parallel.spec import ShardingSpec

    g_mfu = gauge("shard_topology_mfu",
                  "Model FLOPs utilization measured by bench.py shard "
                  "for each mesh topology (analytic trunk FLOPs / best "
                  "window step time / device count x chip peak)",
                  labels=("topology",))

    devs = jax.devices()
    N = int(os.environ.get("BENCH_SHARD_DEVICES", str(len(devs))))
    devs = devs[:N]
    on_tpu = devs[0].platform != "cpu"

    def knob(name, tpu_default, cpu_default):
        return int(os.environ.get(name, str(tpu_default if on_tpu
                                            else cpu_default)))

    H = knob("BENCH_SHARD_HIDDEN", 1024, 64)
    F = knob("BENCH_SHARD_FFN", 4 * H, 4 * H)
    S = knob("BENCH_SHARD_SEQ", 512, 32)
    B = knob("BENCH_SHARD_BATCH", 4 * N, 2 * N if N > 1 else 8)
    L = knob("BENCH_SHARD_LAYERS", 8, 4)
    V = knob("BENCH_SHARD_VOCAB", 8192, 128)
    NH = knob("BENCH_SHARD_HEADS", 16, 4)
    n_micro = knob("BENCH_SHARD_MICRO", 4, 4)
    steps = knob("BENCH_SHARD_STEPS", 10, 4)
    windows = max(2, int(os.environ.get("BENCH_WINDOWS", "3")))
    pairs = max(2, int(os.environ.get("BENCH_SHARD_PAIRS", "3")))
    lr = 0.05
    assert H % NH == 0, (H, NH)

    # ---- the trunk: pre-LN encoder blocks, shared by every topology --
    def _ln(x, g):
        x32 = x.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6) * g).astype(x.dtype)

    def _block_apply(p, x):
        b, s, _ = x.shape
        h = _ln(x, p["ln1"])
        qkv = h @ p["wqkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        hd = H // NH

        def heads(t):
            return t.reshape(b, s, NH, hd).transpose(0, 2, 1, 3)
        q, k, v = heads(q), heads(k), heads(v)
        scores = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
        a = jax.nn.softmax(scores, axis=-1) @ v
        a = a.transpose(0, 2, 1, 3).reshape(b, s, H)
        x = x + a @ p["wo"]
        h2 = _ln(x, p["ln2"])
        return x + jax.nn.relu(h2 @ p["w1"]) @ p["w2"]

    def _block_params(key):
        ks = jax.random.split(key, 4)

        def init(k, a, b):
            return jax.random.normal(k, (a, b), jnp.float32) * (a ** -0.5)
        return {"wqkv": init(ks[0], H, 3 * H), "wo": init(ks[1], H, H),
                "w1": init(ks[2], H, F), "w2": init(ks[3], F, H),
                "ln1": jnp.ones((H,)), "ln2": jnp.ones((H,))}

    def _xent(logits, labels):
        ls = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(ls, labels[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def _trunk_flops(layers):
        """Analytic matmul FLOPs per train step (fwd + 2x bwd), the
        fixed-convention MFU numerator comparable across topologies."""
        per_tok = layers * (2 * H * 3 * H + 2 * H * H + 2 * 2 * H * F
                            + 2 * 2 * S * H) + 2 * H * V
        return 3.0 * B * S * per_tok

    rng = np.random.RandomState(0)
    xb_np = rng.randint(0, V, size=(B, S)).astype(np.int32)
    yb_np = rng.randint(0, V, size=(B, S)).astype(np.int32)

    # ---- topology builders: each returns (step_once, carry, meta) ----
    def build_dense(name, cfg):
        """Pure-DP and model x data: stacked blocks scanned in one jit,
        placement from ONE ShardingSpec (megatron rules inert when the
        model axis has extent 1)."""
        mesh = make_mesh(cfg, devices=devs)
        spec = ShardingSpec(mesh, params={
            "emb": P(), "pos": P(), "head": P(),
            "blocks/wqkv": P(None, None, MODEL_AXIS),
            "blocks/w1": P(None, None, MODEL_AXIS),
            "blocks/wo": P(None, MODEL_AXIS, None),
            "blocks/w2": P(None, MODEL_AXIS, None),
        })
        keys = jax.random.split(jax.random.PRNGKey(0), L + 1)
        params = {
            "emb": jax.random.normal(keys[0], (V, H)) * 0.02,
            "pos": jax.random.normal(keys[0], (S, H)) * 0.02,
            "blocks": pl.stack_stage_params(
                [_block_params(k) for k in keys[1:]]),
            "head": jax.random.normal(keys[0], (H, V)) * 0.02,
        }
        params = spec.place_tree(params)

        def loss_fn(p, xt, yt):
            h = p["emb"][xt] + p["pos"][None]

            def f(x, lp):
                return _block_apply(lp, x), None
            h, _ = jax.lax.scan(f, h, p["blocks"])
            return _xent(h @ p["head"], yt)

        def step(p, xt, yt):
            loss, g = jax.value_and_grad(loss_fn)(p, xt, yt)
            return loss, jax.tree.map(lambda w, gw: w - lr * gw, p, g)

        # in/out shardings PINNED to the spec: the params carry is a
        # true fixed point, so (a) the AOT executable below serves the
        # timed loop directly — one compile total, also feeding the
        # comm estimate its optimized-HLO text — and (b) no hidden
        # step-2 recompile when GSPMD would otherwise drift an
        # unpinned output leaf to a sharded layout
        pshard = spec.tree_shardings(params)
        dsh = NamedSharding(mesh, P(DATA_AXIS))
        rep = NamedSharding(mesh, P())
        jit_step = jax.jit(step, donate_argnums=(0,),
                           in_shardings=(pshard, dsh, dsh),
                           out_shardings=(rep, pshard))
        xt = jax.device_put(xb_np, dsh)
        yt = jax.device_put(yb_np, dsh)
        exe, text = _compile_once(jit_step, params, xt, yt)

        def once(carry):
            loss, new_p = exe(carry, xt, yt)
            return new_p, loss

        return once, params, dict(mesh=cfg, layers=L,
                                  comm=_cost.estimate_comm(text))

    def build_pipe(name, cfg, overlap=None):
        """pipe x data: the fused 1F1B scan (one XLA program for the
        whole trunk) with per-bucket in-scan gradient reduction when
        ``overlap`` is on."""
        import paddle_tpu as pt
        mesh = make_mesh(cfg, devices=devs)
        n_stages = dict(mesh.shape)[PIPE_AXIS]
        keys = jax.random.split(jax.random.PRNGKey(0), n_stages + 1)
        params = {
            "embed": {"w": jax.random.normal(keys[0], (V, H)) * 0.02,
                      "pos": jax.random.normal(keys[0], (S, H)) * 0.02},
            "stages": pl.stack_stage_params(
                [_block_params(k) for k in keys[1:]]),
            "head": {"w": jax.random.normal(keys[0], (H, V)) * 0.02},
        }

        def embed_fn(ep, xt):
            return ep["w"][xt] + ep["pos"][None]

        def loss_fn(hp, a, yt):
            return _xent(a @ hp["w"], yt)

        mod = pl.PipelineModule(mesh, embed_fn, _block_apply, loss_fn,
                                n_micro)
        init_fn, step = mod.make_train_step(
            pt.optimizer.SGDOptimizer(learning_rate=lr),
            schedule="1f1b", overlap_grad_reduce=overlap)
        params, opt_state = init_fn(params)
        xt, yt = jnp.asarray(xb_np), jnp.asarray(yb_np)
        # the module's jitted step keeps auto-commit semantics for the
        # timed loop (its out shardings are not caller-pinnable), so
        # the comm estimate pays one extra AOT compile for the HLO
        # text — pipe topologies only
        _, text = _compile_once(step, params, opt_state, xt, yt)

        def once(carry):
            p, o = carry
            loss, p, o = step(p, o, xt, yt)
            return (p, o), loss

        return once, (params, opt_state), dict(
            mesh=cfg, layers=n_stages,
            comm=_cost.estimate_comm(text))

    def _compile_once(jitted, *args):
        """(AOT executable, optimized-HLO text) from one compile."""
        exe = jitted.lower(*args).compile()
        try:
            text = exe.as_text()
        except Exception:       # backend without HLO text
            text = None
        return exe, text

    model = 2 if N % 2 == 0 else 1
    pipe = 4 if N % 4 == 0 else (2 if N % 2 == 0 else 1)
    wanted = os.environ.get("BENCH_SHARD_TOPOS",
                            "dp,modelxdata,pipexdata").split(",")
    topo_defs = {
        "dp": lambda: build_dense("dp", MeshConfig(data=N)),
        "modelxdata": lambda: build_dense(
            "modelxdata", MeshConfig(data=N // model, model=model)),
        "pipexdata": lambda: build_pipe(
            "pipexdata",
            MeshConfig(data=N // pipe, pipe=pipe,
                       axis_order=("data", "pipe", "model", "seq"))),
    }

    def window(once, carry, n):
        t0 = time.perf_counter()
        for _ in range(n):
            carry, res = once(carry)
        float(np.ravel(np.asarray(res))[0])     # host-fetch sync
        return time.perf_counter() - t0, carry

    # compile + settle every topology BEFORE any timing, then
    # interleave windows round-robin
    runners = {}
    for name in wanted:
        name = name.strip()
        if name not in topo_defs:
            continue
        once, carry, meta = topo_defs[name]()
        dt, carry = window(once, carry, 1)      # compile
        dt, carry = window(once, carry, 2)      # settle the pipeline
        runners[name] = [once, carry, meta, []]
    for w in range(windows):
        for name, r in runners.items():
            dt, r[1] = window(r[0], r[1], steps)
            r[3].append(dt)

    peak = _cost.peak_flops()
    for topo_i, (name, (once, carry, meta, dts)) in enumerate(
            runners.items()):
        best = min(dts)
        ms = best / steps * 1e3
        flops = _trunk_flops(meta["layers"])
        mfu = flops / (best / steps) / (peak * max(N, 1))
        comm = meta["comm"] or {}
        cfg = meta["mesh"]
        g_mfu.set(mfu, topology=name)
        if comm:
            # ONE group for the whole sweep, one segment index per
            # topology: a per-topology group would clear the previous
            # topology's gauge series on every record (latest-group
            # semantics), leaving only the last topology in the
            # end-of-run registry snapshot
            _cost.record_segment_comm("bench.shard", topo_i, comm)
        line = {
            "metric": f"shard_{name}_step_ms",
            "value": round(ms, 3), "unit": "ms",
            # significant digits, not decimal places: a tiny CPU-smoke
            # config's MFU (~1e-7) must not round to a dishonest 0.0
            "mfu": float(f"{mfu:.4g}"),
            "comm_bytes_per_step": comm.get("comm_bytes", 0.0),
            "collectives": comm.get("collectives", {}),
            "tokens_per_sec": round(B * S / (best / steps), 1),
            "layout": {"data": cfg.data, "model": cfg.model,
                       "pipe": cfg.pipe, "n_devices": N},
            "windows_ms_per_step": [round(d / steps * 1e3, 3)
                                    for d in dts],
        }
        spread = (max(dts) - min(dts)) / min(dts) if dts else 0.0
        line["window_spread"] = round(spread, 4)
        if spread > 0.20:
            line["contention_suspected"] = True
        print(json.dumps(line))

    # ---- overlap A/B on the pipe topology (comm-bound config) --------
    from paddle_tpu.parallel.pipeline import _data_reduce_axes
    pmesh_cfg = MeshConfig(data=N // pipe, pipe=pipe,
                           axis_order=("data", "pipe", "model", "seq"))
    pmesh = make_mesh(pmesh_cfg, devices=devs)
    if "pipexdata" in runners and _data_reduce_axes(pmesh):
        on_once, on_carry, on_meta = build_pipe("ov_on", pmesh_cfg,
                                                overlap=True)
        off_once, off_carry, off_meta = build_pipe("ov_off", pmesh_cfg,
                                                   overlap=False)
        onces = {"on": on_once, "off": off_once}
        carries = {"on": on_carry, "off": off_carry}
        for k in ("on", "off"):         # compile + settle
            _, carries[k] = window(onces[k], carries[k], 2)
        on_ms, off_ms, ratios = [], [], []
        for w in range(pairs):
            order = (("on", "off") if w % 2 == 0   # alternate order
                     else ("off", "on"))           # within each pair
            pair = {}
            for k in order:
                pair[k], carries[k] = window(onces[k], carries[k],
                                             steps)
            on_ms.append(pair["on"] / steps * 1e3)
            off_ms.append(pair["off"] / steps * 1e3)
            ratios.append(pair["on"] / pair["off"])
        med = float(np.median(ratios))
        print(json.dumps({
            "metric": "shard_overlap_step_ratio",
            "value": round(med, 4), "unit": "x",
            "overlap_on_ms_per_step": round(float(np.median(on_ms)), 3),
            "overlap_off_ms_per_step": round(float(np.median(off_ms)),
                                             3),
            "pair_ratios": [round(r, 4) for r in ratios],
            "overlap_on_comm_bytes": (on_meta["comm"] or {}).get(
                "comm_bytes", 0.0),
            "overlap_off_comm_bytes": (off_meta["comm"] or {}).get(
                "comm_bytes", 0.0),
            "overlap_on_collectives": (on_meta["comm"] or {}).get(
                "collectives", {}),
            "overlap_off_collectives": (off_meta["comm"] or {}).get(
                "collectives", {}),
        }))
        print(f"# overlap A/B: median pair ratio {med:.4f}x over "
              f"{pairs} interleaved pairs x {steps} steps "
              f"(pipe={pipe}, data={N // pipe})", file=sys.stderr)
    else:
        print("# overlap A/B skipped: pipe topology has no data axis "
              "to reduce over (n_devices too small)", file=sys.stderr)


def bench_kernels():
    """`python bench.py kernels` — per-kernel Pallas-vs-stock A/B.

    One JSON line per registered kernel: interleaved on/off windows
    (the `_abba_overhead` ABBA quadruple idiom — both bodies of each
    ratio sit in the same slice of host drift), value = trimmed-mean
    ratio of Pallas time over stock time (< 1.0 means the Pallas body
    is faster). On CPU the Pallas side runs in interpreter mode at tiny
    shapes — that ratio is a CI liveness check of the exact TPU kernel
    code path, NOT a perf claim; the on-chip re-measure recipe lives in
    docs/PERFORMANCE.md "Pallas kernel layer".

    Env: BENCH_KERNELS_PAIRS (ABBA quadruples per kernel),
    BENCH_KERNELS_ITERS (applications per window)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu.ops.pallas as plk

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    cpu = not on_tpu
    pairs = int(os.environ.get("BENCH_KERNELS_PAIRS",
                               "3" if on_tpu else "2"))
    iters = int(os.environ.get("BENCH_KERNELS_ITERS",
                               "20" if on_tpu else "2"))
    rng = np.random.RandomState(0)

    def f32(*shape):
        return jnp.asarray(rng.randn(*shape), jnp.float32)

    # TPU shapes are the hot-path operating points (BERT-base matmuls,
    # CTR-style embedding traffic, BERT param slabs); CPU shapes are the
    # smallest the kernels' tiling accepts, sized for interpreter mode
    if on_tpu:
        M, K, N = 512, 1024, 4096
        H, D, NI = 65536, 256, 4096
        PSZ = 1 << 20
        B, HH, S, DH = 4, 8, 512, 64
        LN_N, LN_H = 4096, 1024
        XE_N, XE_V = 512, 32000
    else:
        M, K, N = 16, 32, 32
        H, D, NI = 64, 128, 32
        PSZ = 2048
        B, HH, S, DH = 1, 1, 128, 16
        LN_N, LN_H = 16, 64
        XE_N, XE_V = 8, 64

    x, w, bias = f32(M, K), f32(K, N), f32(N)
    w8 = jnp.asarray(rng.randint(-127, 128, (K, N)), jnp.int8)
    scale = jnp.abs(f32(N)) + 0.01
    tbl = f32(H, D)
    ids = jnp.asarray(rng.randint(0, H, NI), jnp.int32)
    upd = f32(NI, D)
    p, g = f32(PSZ), f32(PSZ)
    m1, m2 = jnp.abs(f32(PSZ)), jnp.abs(f32(PSZ))
    lr, t = jnp.float32(1e-3), jnp.int32(10)
    q, kk, vv = f32(B, HH, S, DH), f32(B, HH, S, DH), f32(B, HH, S, DH)
    gam, bet, xln = f32(LN_H), f32(LN_H), f32(LN_N, LN_H)
    logits = f32(XE_N, XE_V)
    labels = jnp.asarray(rng.randint(0, XE_V, XE_N), jnp.int32)

    cases = [
        ("kernel_matmul_ratio", "fused_matmul", (x, w),
         {"bias": bias, "act": "relu"}),
        ("kernel_matmul_int8_ratio", "fused_matmul_int8", (x, w8, scale),
         {"bias": bias}),
        ("kernel_embedding_ratio", "embedding_gather", (tbl, ids), {}),
        ("kernel_scatter_add_ratio", "embedding_scatter_add",
         (tbl, ids, upd), {}),
        ("kernel_optimizer_ratio", "fused_adam", (p, g, m1, m2, lr, t),
         {}),
        ("kernel_attention_ratio", "flash_attention", (q, kk, vv),
         {"causal": True}),
        ("kernel_layer_norm_ratio", "fused_layer_norm", (xln, gam, bet),
         {}),
        ("kernel_xent_ratio", "softmax_cross_entropy", (logits, labels),
         {}),
    ]

    body_label = "pallas_interpret" if cpu else "pallas"
    for metric, kname, args, kw in cases:
        # jit both bodies directly from the registry: `override()` can't
        # retrace an already-cached jit, so the A/B pins each side to a
        # dedicated compiled callable
        def make(fn, force_interpret):
            kw2 = dict(kw)
            if force_interpret:
                kw2["interpret"] = True

            def apply(*a):
                out = fn(*a, **kw2)
                return sum(jnp.sum(leaf.astype(jnp.float32))
                           for leaf in jax.tree.leaves(out))

            return jax.jit(apply)

        f_on = make(plk.get_body(kname, "pallas"), cpu)
        f_off = make(plk.get_body(kname, "reference"), False)
        f_on(*args).block_until_ready()       # compile outside windows
        f_off(*args).block_until_ready()

        def window(on, _fs=(f_on, f_off)):
            f = _fs[0] if on else _fs[1]
            t0 = time.perf_counter()
            r = None
            for _ in range(iters):
                r = f(*args)
            r.block_until_ready()
            return (time.perf_counter() - t0) / iters

        est, pair_ratios, on_ts, off_ts = _abba_overhead(
            window, pairs, rounds=0)
        print(json.dumps({
            "metric": metric, "value": round(est, 4), "unit": "x",
            "kernel": kname, "body": body_label,
            "pallas_ms": round(float(np.min(on_ts)) * 1e3, 4),
            "stock_ms": round(float(np.min(off_ts)) * 1e3, 4),
            "pairs": len(pair_ratios), "iters": iters,
            "platform": dev.platform,
        }))


def _emit_registry_snapshot():
    """End-of-run metrics emission: the registry (bench windows +
    whatever executor/prefetch/checkpoint counters the run touched) as
    Prometheus text — to the BENCH_METRICS_OUT path when set, else a
    compact dump on stderr. Never fatal: a bench must not fail on its
    own telemetry."""
    try:
        from paddle_tpu.monitor import exporter
        out = os.environ.get("BENCH_METRICS_OUT")
        if out:
            exporter.write_snapshot(out)
            print(f"# metrics registry snapshot -> {out}",
                  file=sys.stderr)
        else:
            print("# --- metrics registry snapshot ---",
                  file=sys.stderr)
            print(exporter.render_text(), file=sys.stderr, end="")
    except Exception as e:   # pragma: no cover - telemetry-only path
        print(f"# metrics snapshot failed: {e}", file=sys.stderr)


def _emit_peak_hbm():
    """End-of-run device-memory line, emitted for EVERY mode: one
    final live-buffer sample (monitor/memory.py) folded into the
    high-water mark — the run's peak when the poller was on, its
    end-of-run residency floor otherwise (``sampled`` says which).
    Never fatal: a bench must not fail on its own telemetry."""
    try:
        from paddle_tpu.monitor import memory as _memory
        sampled = _memory.poller_enabled()
        _memory.sample_now()
        print(json.dumps({
            "metric": "peak_hbm_bytes",
            "value": int(_memory.high_water()),
            "unit": "bytes", "sampled_continuously": sampled,
        }))
    except Exception as e:   # pragma: no cover - telemetry-only path
        print(f"# peak_hbm_bytes failed: {e}", file=sys.stderr)


def main():
    try:
        return _dispatch_mode()
    finally:
        _emit_peak_hbm()
        _emit_registry_snapshot()


def _dispatch_mode():
    if len(sys.argv) > 1 and sys.argv[1] == "dispatch":
        # executor host-overhead microbench (small model: the step time
        # IS the dispatch); lives in bench_dispatch.py, reuses this
        # module's _timed_steps harness
        import bench_dispatch
        return bench_dispatch.main()
    if len(sys.argv) > 1 and sys.argv[1] == "resnet50":
        return bench_resnet50()
    if len(sys.argv) > 1 and sys.argv[1] == "nmt":
        return bench_nmt()
    if len(sys.argv) > 1 and sys.argv[1] == "inference":
        return bench_inference()
    if len(sys.argv) > 1 and sys.argv[1] == "longcontext":
        return bench_longcontext()
    if len(sys.argv) > 1 and sys.argv[1] == "int8":
        return bench_int8()
    if len(sys.argv) > 1 and sys.argv[1] == "passes":
        return bench_passes()
    if len(sys.argv) > 1 and sys.argv[1] == "serving":
        return bench_serving()
    if len(sys.argv) > 1 and sys.argv[1] == "numerics":
        return bench_numerics()
    if len(sys.argv) > 1 and sys.argv[1] == "ckpt":
        return bench_ckpt()
    if len(sys.argv) > 1 and sys.argv[1] == "data":
        return bench_data()
    if len(sys.argv) > 1 and sys.argv[1] == "shard":
        return bench_shard()
    if len(sys.argv) > 1 and sys.argv[1] == "kernels":
        return bench_kernels()
    import jax
    import jax.numpy as jnp

    import paddle_tpu as pt
    from paddle_tpu.models import bert
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh, set_mesh

    dev = jax.devices()[0]
    on_tpu = dev.platform != "cpu"
    # BENCH_ATTN=dense|flash selects the attention path (flash = Pallas
    # blockwise kernel, ops/pallas_kernels.py) for A/B runs on the chip
    attn = os.environ.get("BENCH_ATTN", "dense")
    # remat off: BERT-base bs=64 seq=512 activations fit v5e HBM, and
    # skipping the recompute is worth ~+0.06 MFU (measured 0.418 vs 0.362;
    # bs>=96 fails to compile -- OOM -- so bs=64 no-remat is the frontier)
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    # bf16 softmax: r4 on-chip A/B measured 154.2k vs 152.2k tok/s at
    # spc=8 with matching loss curves (full experiment matrix in
    # BASELINE.md "BERT MFU experiments"); BENCH_SOFTMAX=fp32 reverts
    smax = os.environ.get("BENCH_SOFTMAX", "bf16" if on_tpu else "fp32")
    cfg = (bert.bert_base(attention_impl=attn, remat=remat,
                          softmax_dtype=smax) if on_tpu
           else bert.bert_tiny(attention_impl=attn))
    # batch=64 is the tuned single-chip config (highest measured MFU of
    # {32, 64, 96}); vs_baseline is MFU-based, so it stays comparable
    # across batch choices
    batch, seq = (64, 512) if on_tpu else (2, 32)
    steps = 20 if on_tpu else 3

    # single-chip benchmark: pin a 1-device mesh whatever the platform
    mesh = set_mesh(make_mesh(MeshConfig(data=1),
                              devices=jax.devices()[:1]))
    opt = pt.optimizer.Adam(learning_rate=1e-4)
    # 16 scanned steps per dispatch (train_from_dataset pattern):
    # amortizes the remote-PJRT dispatch gap, same batch per inner step.
    # r4 A/B on-chip: spc=16 155.1k tok/s vs spc=8 154.2k (with bf16
    # softmax; r3: spc=8 153.2k vs spc=4 152.0-152.7k). BENCH_SPC
    # overrides.
    spc = int(os.environ.get("BENCH_SPC", "16" if on_tpu else "1"))
    init_fn, step_fn = bert.make_train_step(cfg, opt, mesh,
                                            steps_per_call=spc)
    # gathered MLM head: predict only max_predictions_per_seq positions
    # (80 ~= 0.15*512, BERT pretraining's standard), not all S — the
    # vocab head is 20% of model FLOPs and this is how the objective is
    # defined; +29% tokens/sec measured, MFU accounted at reduced FLOPs
    max_preds = int(os.environ.get("BENCH_MAX_PREDS",
                                   "80" if on_tpu else "4"))
    data = bert.synthetic_batch(cfg, batch_size=batch, seq_len=seq,
                                max_preds=max_preds)
    params, opt_state = init_fn(jax.random.PRNGKey(0))

    def once(carry):
        params, opt_state = carry
        loss, params, opt_state = step_fn(params, opt_state, data)
        return (params, opt_state), loss

    tr = _timed_steps(once, (params, opt_state), steps, sub_steps=spc)
    loss = tr.res

    tokens = batch * seq * steps * spc
    tok_per_sec = tokens / tr.dt
    # MFU vs bf16 peak (v5e ~197 TFLOP/s; other gens still get a number)
    peak = 197e12
    flops = bert.flops_per_token(cfg, seq_len=seq, max_preds=max_preds)
    mfu = tok_per_sec * flops / peak
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tok_per_sec, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(mfu / 0.35, 4),
        **tr.extras(),
    }))
    print(f"# device={dev.platform} batch={batch} seq={seq} steps={steps} "
          f"loss={float(loss):.4f} mfu={mfu:.3f}", file=sys.stderr)


if __name__ == "__main__":
    main()
